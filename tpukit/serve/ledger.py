"""Durable request lifecycle + real-process fleet serving (round 24).

ROADMAP #1(b)'s gap, closed: through round 23 the FleetRouter's queue,
per-replica assignments and completion ledger lived in ONE process's
memory, so `replica_kill` chaos could only SIMULATE death — a replica
process actually dying (SIGKILL, OOM, preemption) lost every in-flight
and queued request. This module makes the request lifecycle crash-
consistent, file-backed under `--fleet_dir`:

  - **RequestLedger** — the durable lifecycle store. One atomic JSON file
    per record (the `fsio.atomic_write_text` one-spelling, every
    read/write riding `retry.retry_io` under the `ledger` chaos site):

        stream.json            the full request stream, written ONCE
                               ahead of serving (the replay source)
        assign/r<rid>.json     the request's current LEASE {replica,
                               attempt, t} — written BEFORE dispatch
                               (write-ahead), overwritten on requeue
        done/r<rid>.json       the completion record {ids, reason,
                               timings} — written AFTER the tokens exist
        failed/r<rid>.json     terminal non-completion (retry budget
                               exhausted, backpressure rejection)
        dup/r<rid>-a<n>.json   a detected duplicate-completion attempt
                               (the exactly-once invariant as data: CI
                               asserts this directory stays empty)
        heartbeats/replica-<i>.json   liveness plane (recovery.py's
                               heartbeat-file discipline)
        ctl/stop.json, ctl/stall-<i>.json   control records (shutdown,
                               slow_replica chaos)

    Exactly-once completion is STRUCTURAL: one done file per rid, and
    `complete()` checks-then-publishes — a second completion of the same
    rid (a lease revoked from a replica that was slow, not dead) is
    detected, recorded under dup/, and never overwrites the first.
    Replay (`open_stream` on a non-empty directory) filters completed
    rids out of the stream, so a restarted router resumes at the exact
    pre-crash frontier; open leases simply re-serve (write-ahead gives
    at-least-once ASSIGNMENT, the done-file gives exactly-once OUTPUT).

  - **serve_from_ledger** — the replica worker loop: an OS process owning
    one ServeEngine claims leases naming its replica id from the ledger,
    serves them, publishes completions and heartbeats. Workers never talk
    to each other — the ledger directory is the only channel, which is
    exactly what makes SIGKILL recoverable.

  - **ProcessFleet** — the supervisor: spawns N workers (via a caller-
    provided `spawn`, so recipes re-exec themselves and tests launch a
    worker script), assigns leases least-loaded, watches liveness (a
    worker is dead when its process exited OR its heartbeat is older
    than `replica_timeout` — the straggler/dead discrimination the
    `slow_replica` chaos drills), revokes a dead worker's leases and
    requeues them with a jittered backoff under the `--request_retries`
    budget, and fires `replica_sigkill` chaos as REAL `os.kill`.

The failure plane is pure host-side control: no compiled program changes
(the decode-step comm plan is byte-identical with the ledger on — the
hlolint acceptance this round rides on the round-19 worlds unchanged).
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import time
from collections import deque
from pathlib import Path

from tpukit import chaos as chaos_lib
from tpukit import recovery as recovery_lib
from tpukit import retry as retry_lib
from tpukit.fsio import atomic_write_text
from tpukit.serve.engine import Completion, Request


# ---------------------------------------------------------------------------
# Raw ledger I/O (the chaos-injectable, retry-wrapped primitives).
# lint_invariants' retry-io rule covers these two names: they may be
# passed TO retry_io but never called directly — a bare call would opt
# that record out of the transient-fault budget the `ledger_io_fail`
# chaos drills.
# ---------------------------------------------------------------------------


def _write_rec(path: Path, obj: dict) -> None:
    chaos_lib.maybe_io_fault("ledger")
    atomic_write_text(Path(path), json.dumps(obj, sort_keys=True))


def _read_rec(path: Path) -> dict:
    chaos_lib.maybe_io_fault("ledger")
    return json.loads(Path(path).read_text())


def request_to_rec(req: Request) -> dict:
    return dict(
        rid=req.rid, ids=[int(i) for i in req.ids],
        max_new_tokens=req.max_new_tokens, seed=req.seed,
        arrival_s=req.arrival_s, trace=req.trace,
        deadline_ms=req.deadline_ms, priority=req.priority,
    )


def request_from_rec(rec: dict) -> Request:
    return Request(
        rid=int(rec["rid"]), ids=tuple(int(i) for i in rec["ids"]),
        max_new_tokens=int(rec["max_new_tokens"]), seed=int(rec["seed"]),
        arrival_s=float(rec["arrival_s"]), trace=int(rec.get("trace", -1)),
        deadline_ms=float(rec.get("deadline_ms", 0.0)),
        priority=int(rec.get("priority", 0)),
    )


class RequestLedger:
    """The durable request lifecycle store rooted at one directory (see
    the module docstring for the record layout). Every method is safe to
    call from the router/supervisor AND from worker processes — records
    are single atomic files, readers tolerate files appearing between
    list and read, and the only multi-writer path (done/) is
    check-then-publish with duplicates detected, not interleaved."""

    def __init__(self, directory: str | Path):
        self.dir = Path(directory)
        for sub in ("assign", "done", "failed", "dup", "heartbeats", "ctl"):
            (self.dir / sub).mkdir(parents=True, exist_ok=True)
        self._stream_path = self.dir / "stream.json"

    # -- request stream (write-ahead + replay) -----------------------------

    def open_stream(self, requests: list[Request]) -> tuple[list[Request], dict]:
        """Write the stream ahead of serving (first open) or replay it
        (restart: the stream file survives, completed rids filter out).
        Returns (requests still to serve, completed records by rid)."""
        if not self._stream_path.exists():
            retry_lib.retry_io(
                _write_rec, self._stream_path,
                {"requests": [request_to_rec(r) for r in requests]},
                label="ledger_write",
            )
        done = self.completions()
        failed = self.failures()
        todo = [r for r in requests
                if r.rid not in done and r.rid not in failed]
        return todo, done

    def read_stream(self) -> list[Request]:
        rec = retry_lib.retry_io(_read_rec, self._stream_path,
                                 label="ledger_read")
        return [request_from_rec(r) for r in rec["requests"]]

    def has_stream(self) -> bool:
        return self._stream_path.exists()

    # -- leases ------------------------------------------------------------

    def assign(self, rid: int, replica: int, attempt: int, t: float) -> None:
        """Publish the request's current lease — WRITE-AHEAD: this lands
        before the replica sees the request, so a crash between assign
        and dispatch replays as a requeue, never a lost request."""
        retry_lib.retry_io(
            _write_rec, self.dir / "assign" / f"r{rid:06d}.json",
            dict(rid=rid, replica=replica, attempt=attempt, t=t),
            label="ledger_write",
        )

    def assignments(self) -> dict[int, dict]:
        return self._scan("assign")

    # -- completions (exactly-once publish) --------------------------------

    def complete(self, comp: Completion, replica, attempt: int) -> bool:
        """Publish a completion record; returns False (and records the
        attempt under dup/) when the rid already has one — the second
        finisher of a twice-served request must never overwrite the
        tokens the first one already emitted."""
        path = self.dir / "done" / f"r{comp.rid:06d}.json"
        if path.exists():
            retry_lib.retry_io(
                _write_rec,
                self.dir / "dup" / f"r{comp.rid:06d}-a{attempt}.json",
                dict(rid=comp.rid, replica=replica, attempt=attempt),
                label="ledger_write",
            )
            return False
        retry_lib.retry_io(
            _write_rec, path,
            dict(rid=comp.rid, replica=replica, attempt=attempt,
                 ids=[int(i) for i in comp.ids],
                 prompt_len=comp.prompt_len, generated=comp.generated,
                 reason=comp.reason, arrival_s=comp.arrival_s,
                 admit_s=comp.admit_s, done_s=comp.done_s,
                 e2e_s=comp.e2e_s),
            label="ledger_write",
        )
        return True

    def completions(self) -> dict[int, dict]:
        return self._scan("done")

    def duplicates(self) -> int:
        return len(list((self.dir / "dup").glob("*.json")))

    # -- terminal failures -------------------------------------------------

    def record_failure(self, rid: int, reason: str, attempts: int) -> None:
        retry_lib.retry_io(
            _write_rec, self.dir / "failed" / f"r{rid:06d}.json",
            dict(rid=rid, reason=reason, attempts=attempts),
            label="ledger_write",
        )

    def failures(self) -> dict[int, dict]:
        return self._scan("failed")

    # -- liveness + control ------------------------------------------------

    def beat(self, replica: int, **fields) -> None:
        """Worker heartbeat: wall-clock stamped (the one cross-process
        clock), one atomic file per replica — recovery.py's discipline."""
        retry_lib.retry_io(
            recovery_lib.publish_heartbeat, self.dir / "heartbeats",
            f"replica-{replica:05d}",
            dict(replica=replica, t=time.time(), **fields),
            label="heartbeat",
        )

    def heartbeats(self) -> dict[int, dict]:
        out = {}
        for rec in recovery_lib.read_heartbeat_dir(
            self.dir / "heartbeats", "replica-"
        ).values():
            out[int(rec["replica"])] = rec
        return out

    def request_stop(self) -> None:
        retry_lib.retry_io(_write_rec, self.dir / "ctl" / "stop.json",
                           dict(t=time.time()), label="ledger_write")

    def stop_requested(self) -> bool:
        return (self.dir / "ctl" / "stop.json").exists()

    def set_stall(self, replica: int, stall_s: float, token: int) -> None:
        """slow_replica chaos control: the worker sleeps `stall_s` without
        beating, once per unseen `token` — a straggler, not a corpse."""
        retry_lib.retry_io(
            _write_rec, self.dir / "ctl" / f"stall-{replica:05d}.json",
            dict(replica=replica, stall_s=stall_s, token=token),
            label="ledger_write",
        )

    def read_stall(self, replica: int) -> dict | None:
        path = self.dir / "ctl" / f"stall-{replica:05d}.json"
        if not path.exists():
            return None
        return retry_lib.retry_io(_read_rec, path, label="ledger_read")

    # -- internals ---------------------------------------------------------

    def _scan(self, sub: str) -> dict[int, dict]:
        """Read every r<rid>.json record in a subdirectory, keyed by rid.
        A file vanishing between glob and read would be an OSError —
        retried, then fatal; ledger records are never deleted, so that
        only happens on real filesystem trouble."""
        out: dict[int, dict] = {}
        for path in sorted((self.dir / sub).glob("r*.json")):
            rec = retry_lib.retry_io(_read_rec, path, label="ledger_read")
            out[int(rec["rid"])] = rec
        return out


# ---------------------------------------------------------------------------
# The replica worker loop (one OS process, one engine)
# ---------------------------------------------------------------------------


def serve_from_ledger(engine, directory: str | Path, replica: int, *,
                      poll_s: float = 0.005, max_wall_s: float = 600.0,
                      stream_wait_s: float = 60.0) -> list[Completion]:
    """Serve leases addressed to `replica` from the ledger until the
    supervisor publishes stop (or `max_wall_s` hard-stops a supervisor
    that died). The loop per tick: honor a stall control record (sleep
    WITHOUT beating — the slow_replica fault is genuine slowness, not
    scripted death), beat the heartbeat, claim newly-assigned requests,
    drive the engine one quantum, publish fresh completions.

    A claimed request's `arrival_s` is rewritten to the claim time on the
    worker's run clock — deadlines and e2e latencies are measured from
    when THIS attempt could first run (the lease timestamps in the ledger
    keep the cross-process queue history). Token output is unaffected:
    parity rides only on prompt + per-request seed."""
    led = RequestLedger(directory)
    t0 = time.time()
    while not led.has_stream():
        if time.time() - t0 > stream_wait_s:
            raise TimeoutError(
                f"replica {replica}: no stream.json after {stream_wait_s}s"
            )
        time.sleep(poll_s)
    by_rid = {r.rid: r for r in led.read_stream()}
    queue: deque[Request] = deque()
    claimed: dict[int, int] = {}  # rid -> lease attempt served/serving
    published = 0
    beats = 0
    stall_seen = -1
    while True:
        now = time.time() - t0
        if now > max_wall_s:
            break
        stall = led.read_stall(replica)
        if stall is not None and int(stall.get("token", 0)) > stall_seen:
            stall_seen = int(stall["token"])
            time.sleep(float(stall["stall_s"]))
            continue
        beats += 1
        led.beat(replica, pid=os.getpid(), beats=beats,
                 generated=engine.generated_tokens, lanes=engine.live_lanes)
        done = led.completions()
        for rid, lease in sorted(led.assignments().items()):
            if (lease["replica"] == replica and rid in by_rid
                    and rid not in done
                    and claimed.get(rid) != lease["attempt"]):
                claimed[rid] = int(lease["attempt"])
                queue.append(dataclasses.replace(by_rid[rid], arrival_s=now))
        if queue:
            batch = []
            while queue and len(batch) < engine.free_slots:
                batch.append(queue.popleft())
            for req in reversed(engine.admit(batch, now)):
                queue.appendleft(req)
        engine.poll_prefill(time.time() - t0)
        progressed = engine.dispatch_decode()
        if progressed:
            engine.sync(time.time() - t0)
        comps = engine.completions
        for c in comps[published:]:
            led.complete(c, replica=replica, attempt=claimed.get(c.rid, 1))
        published = len(comps)
        if led.stop_requested() and not queue and engine.live_lanes == 0:
            break
        if not progressed and not queue:
            time.sleep(poll_s)
    return engine.finish(time.time() - t0)


# ---------------------------------------------------------------------------
# The supervisor (real-process fleet)
# ---------------------------------------------------------------------------


class ProcessFleet:
    """Crash-tolerant fleet of worker PROCESSES over one ledger directory.

    `spawn(idx)` launches replica worker `idx` and returns its
    subprocess.Popen — the recipe re-execs itself with `--fleet_worker
    idx`, tests launch a worker script. The supervisor owns assignment
    (least open leases, lowest id), liveness (process exit OR heartbeat
    age > `replica_timeout`), lease revocation + budgeted requeue with
    jittered backoff (`retry.backoff_delay` — survivors must not be
    hammered in lockstep), and the serving chaos plan (`replica_sigkill`
    as real `os.kill`; round indices count supervisor polls WITH WORK IN
    FLIGHT, so a scheduled fault always has leases to disrupt). A dead
    replica is respawned only when it was the LAST one — otherwise
    survivors absorb the work, the round-19 requeue semantics."""

    def __init__(self, directory: str | Path, *, spawn, replicas: int,
                 replica_timeout: float = 5.0, request_retries: int = 3,
                 chaos: chaos_lib.ServingChaos | None = None,
                 logger=None, recorder=None, poll_s: float = 0.01,
                 grace_s: float = 20.0):
        if replicas < 1:
            raise ValueError(f"replicas={replicas} must be >= 1")
        self.ledger = RequestLedger(directory)
        self.spawn = spawn
        self.replicas = replicas
        self.replica_timeout = replica_timeout
        self.request_retries = request_retries
        self.chaos = chaos
        self.logger = logger
        self.recorder = recorder
        self.poll_s = poll_s
        self.grace_s = grace_s
        self.kills = 0
        self.requeued = 0
        self.replicas_dead = 0
        self.leases_revoked = 0
        self.respawns = 0
        self._deaths: list[dict] = []

    def _event(self, event: str, **kw) -> None:
        if self.logger is not None:
            self.logger.log(kind="fleet_event", event=event, **kw)
        if self.recorder is not None:
            self.recorder.record("fleet_event", event=event, **kw)

    def _pick_target(self, target: int | None, procs: dict) -> int | None:
        live = sorted(procs)
        if len(live) <= 1:
            return None
        return target if target in procs else live[-1]

    def run(self, requests: list[Request],
            max_wall_s: float = 300.0) -> dict:
        """Serve `requests` to the terminal frontier (every rid completed
        or terminally failed); returns the `kind="fleet_summary"` record.
        Raises TimeoutError past `max_wall_s` — a fleet that cannot
        converge must fail loud, not hang CI."""
        led = self.ledger
        todo, replayed = led.open_stream(requests)
        all_rids = {r.rid for r in requests}
        prev_chaos = chaos_lib.install(self.chaos)
        rlog = retry_lib.RetryLog()
        retry_lib.set_observer(rlog)
        procs: dict[int, object] = {}
        spawn_t: dict[int, float] = {}
        try:
            for i in range(self.replicas):
                procs[i] = self.spawn(i)
                spawn_t[i] = time.time()
            attempts: dict[int, int] = {}
            not_before: dict[int, float] = {}
            unassigned = {r.rid for r in todo}
            failed: set[int] = set(led.failures())
            rounds = 0
            t0 = time.time()
            while True:
                now = time.time() - t0
                if now > max_wall_s:
                    raise TimeoutError(
                        f"process fleet exceeded max_wall_s={max_wall_s} "
                        f"with {len(unassigned)} unassigned"
                    )
                done = led.completions()
                if all_rids <= (set(done) | failed):
                    break
                leases = led.assignments()
                open_leases = {
                    rid: l for rid, l in leases.items()
                    if rid not in done and rid not in failed
                    and rid not in unassigned
                }
                # chaos fires on rounds WITH work in flight
                if open_leases:
                    rounds += 1
                    self._fire_chaos(rounds, procs)
                self._check_liveness(procs, spawn_t, open_leases,
                                     attempts, not_before, unassigned,
                                     failed, now)
                if not procs:
                    # every replica died with work outstanding: respawn
                    # replica 0 — the restarted-router half of crash
                    # consistency (the ledger replays its frontier)
                    procs[0] = self.spawn(0)
                    spawn_t[0] = time.time()
                    self.respawns += 1
                    self._event("replica_respawn", replica=0)
                loads = {i: 0 for i in procs}
                for lease in open_leases.values():
                    if lease["replica"] in loads:
                        loads[lease["replica"]] += 1
                for rid in sorted(unassigned):
                    if not_before.get(rid, 0.0) > now:
                        continue
                    target = min(procs, key=lambda i: (loads[i], i))
                    att = attempts.get(rid, 0) + 1
                    attempts[rid] = att
                    led.assign(rid, target, att, now)
                    loads[target] += 1
                    unassigned.discard(rid)
                time.sleep(self.poll_s)
            wall = time.time() - t0
        finally:
            led.request_stop()
            exit_codes = self._reap(procs)
            chaos_lib.install(prev_chaos)
            retry_lib.set_observer(None)
        return self._summary(requests, replayed, failed, wall, exit_codes,
                             rlog, attempts)

    def _fire_chaos(self, rounds: int, procs: dict) -> None:
        ch = self.chaos
        if ch is None:
            return
        # in --fleet_procs mode replica_kill means the same thing as
        # replica_sigkill: there is no in-process engine to drop, death
        # IS the process dying
        targets = (ch.sigkills.pop(rounds, [])
                   + ch.kills.pop(rounds, []))
        for target in targets:
            idx = self._pick_target(target, procs)
            if idx is None:
                self._event("kill_skipped", round=rounds,
                            reason="last live replica")
                continue
            os.kill(procs[idx].pid, signal.SIGKILL)
            self.kills += 1
            ch.record(dict(fault="replica_sigkill", round=rounds,
                           replica=idx, pid=procs[idx].pid))
            self._event("replica_sigkill", replica=idx, round=rounds,
                        pid=procs[idx].pid)
        for stall_s in ch.stalls.pop(rounds, []):
            live = sorted(procs)
            idx = live[-1]
            self.ledger.set_stall(idx, stall_s, token=rounds)
            ch.record(dict(fault="slow_replica", round=rounds,
                           replica=idx, stall_s=stall_s))
            self._event("replica_slow", replica=idx, round=rounds,
                        stall_s=stall_s)

    def _check_liveness(self, procs, spawn_t, open_leases, attempts,
                        not_before, unassigned, failed, now) -> None:
        beats = self.ledger.heartbeats()
        wall = time.time()
        for idx in sorted(procs):
            code = procs[idx].poll()
            reason = None
            if code is not None:
                reason = dict(reason="exit", code=code)
            elif self.replica_timeout > 0:
                rec = beats.get(idx)
                t = rec["t"] if rec else spawn_t[idx]
                age = wall - t
                if age > self.replica_timeout:
                    reason = dict(reason="heartbeat_timeout",
                                  age_s=round(age, 3))
            if reason is None:
                continue
            proc = procs.pop(idx)
            if code is None:
                # heartbeat-dead but process-alive: fence it so it can
                # never race a survivor for its revoked leases
                try:
                    proc.kill()
                except OSError:
                    pass
            self.replicas_dead += 1
            self._deaths.append(dict(replica=idx, **reason))
            victims = sorted(
                rid for rid, l in open_leases.items()
                if l["replica"] == idx
            )
            self.leases_revoked += len(victims)
            requeue_rids = []
            for rid in victims:
                open_leases.pop(rid, None)
                n = attempts.get(rid, 1)
                if n > self.request_retries:
                    failed.add(rid)
                    self.ledger.record_failure(rid, "retry_budget", n)
                    self._event("request_failed", rid=rid, attempts=n,
                                reason="retry_budget")
                else:
                    not_before[rid] = now + retry_lib.backoff_delay(n)
                    unassigned.add(rid)
                    requeue_rids.append(rid)
            self.requeued += len(requeue_rids)
            self._event("replica_dead", replica=idx, **reason,
                        requeued=len(requeue_rids),
                        requeued_rids=requeue_rids)
            if self.logger is not None and requeue_rids:
                self.logger.log(kind="lease_requeue", from_replica=idx,
                                rids=requeue_rids,
                                attempts={str(r): attempts.get(r, 1)
                                          for r in requeue_rids})

    def _reap(self, procs: dict) -> dict[int, int | None]:
        codes: dict[int, int | None] = {}
        deadline = time.time() + self.grace_s
        for idx, p in sorted(procs.items()):
            while p.poll() is None and time.time() < deadline:
                time.sleep(0.05)
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=5)
                except Exception:
                    p.kill()
                    p.wait()
            codes[idx] = p.poll()
        return codes

    def _summary(self, requests, replayed, failed, wall, exit_codes,
                 rlog, attempts) -> dict:
        done = self.ledger.completions()
        e2e = sorted(float(r.get("e2e_s", 0.0)) for r in done.values())
        rids = sorted(done)
        gen = sum(int(r["generated"]) for r in done.values())
        pct = lambda q: (  # noqa: E731
            e2e[min(int(q / 100 * len(e2e)), len(e2e) - 1)] if e2e else None
        )
        rec = dict(
            kind="fleet_summary", mode="procs", requests=len(done),
            generated_tokens=gen, wall_s=wall,
            tokens_per_sec=(gen / wall) if wall else None,
            replicas_final=self.replicas - self.replicas_dead
            + self.respawns,
            replicas_peak=self.replicas,
            scale_ups=0, scale_downs=0,
            kills=self.kills, requeued=self.requeued,
            duplicate_completions=self.ledger.duplicates(),
            p50_e2e_s=pct(50), p99_e2e_s=pct(99),
            per_replica={}, occupancy_spread=0.0,
            params_placements=self.replicas,
            replicas_dead=self.replicas_dead,
            leases_revoked=self.leases_revoked,
            deadline_misses=sum(
                1 for r in done.values() if r["reason"] == "deadline"
            ),
            request_failures=len(failed), rejected=0,
            respawns=self.respawns, deaths=self._deaths,
            worker_exit_codes={str(k): v for k, v in exit_codes.items()},
            retry_total=rlog.total,
            ledger=dict(
                completed=len(rids), replayed=len(replayed),
                duplicates=self.ledger.duplicates(),
                max_attempts=max(attempts.values()) if attempts else 0,
            ),
        )
        if self.chaos is not None:
            for ev in self.chaos.drain_fired():
                if self.logger is not None:
                    self.logger.log(kind="chaos", **ev)
        if self.logger is not None:
            self.logger.log(**rec)
        if self.recorder is not None:
            self.recorder.record(
                "fleet_summary", requests=rec["requests"],
                tokens_per_sec=rec["tokens_per_sec"],
                requeued=rec["requeued"], kills=rec["kills"],
            )
        return rec
