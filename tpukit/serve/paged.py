"""Paged KV cache: fixed-size pages + per-slot block tables (round 15).

ROADMAP open item 2. The round-14 serving engine preallocates every decode
lane at the full KV-ring width — a 20-token answer in a wide slot strands
almost all of its KV HBM, and the worst-case request sets the slot count
(i.e. the throughput ceiling) for everyone. This module replaces the
per-slot ring with the layout real serving engines use (vLLM's
PagedAttention, PAPERS.md):

  - **Page pool**: one `[L, num_pages, H, P, D]` K buffer and one V buffer
    (P = `page_size` token positions per page). Page 0 is the reserved
    NULL page — never allocated, the sink for masked writes — so a block
    table full of zeros is always safe to dereference.
  - **Block tables**: per-slot `[N, pages_per_slot]` int32 rows of page
    ids. The decode step dereferences them with ONE gather per layer
    (`gather_view`) into exactly the `[N, H, W, D]` per-row view the
    round-14 vector-cursor attention already consumes — the indirection is
    localized in `gpt._apply_attention_cached`'s paged branch and the
    decode-step math is otherwise byte-for-byte the ring path, which is
    what keeps the token-for-token parity bar provable.
  - **Allocation at request granularity**: a request admitted with prompt
    length p and budget m holds `ceil(min(p + m, width) / P)` pages — its
    actual worst case — instead of a full-width slot. The HBM a short
    answer strands is at most one page, and the pool (not the widest
    request) sets the concurrency ceiling.
  - **Shared-prefix reuse**: prompt prefixes are hashed at page
    granularity into a chained registry (parent-page + chunk-tokens ->
    page). A new request walks the registry, points its block table at
    the matched read-only pages with refcounts, and skips the shared
    portion of prefill entirely. Refcount-0 registered pages are RETAINED
    (LRU) and reclaimed only under pool pressure, so a popular system
    prompt stays hot across non-overlapping requests.
  - **int8 page payloads** (`kv_dtype="int8"`): page rows quantized with
    `ops.quant_comm`'s per-256-element block quantizer (EQuARX layout,
    round 12) — one f32 scale per 256 elements of the flattened
    `[P, D]` row per head, payload int8 — for ~4x pages per HBM byte vs
    f32 (~2x vs bf16). Quantization is lossy by construction, so int8 KV
    is gated by a token-level tolerance test (tests/test_paged.py),
    mirroring the round-12 loss-trajectory gate; f32/bf16 page storage at
    the matching compute dtype stays token-for-token exact.

Write-safety invariants (everything here leans on them):

  1. A slot's WRITABLE pages are exclusively owned. Shared (registered)
     pages are capped at `(prompt_len - 1) // P` — the page holding
     position `prompt_len - 1` is always private, because the first
     decode tick re-forwards the last prompt token and rewrites that
     position's K/V (identical values, but a write nonetheless — and
     under int8 a block REQUANTIZATION, which must never touch a page
     another slot reads).
  2. Masked rows (inactive/free slots, padded admit lanes) write to page
     0. The engine zeroes a freed slot's block-table row, so even a stale
     in-flight write after eviction lands in the null page, never in a
     page the allocator has re-issued.
  3. Reads beyond a slot's logical cursor hit garbage (the null page, an
     unwritten tail, a recycled page's old contents) — and are masked by
     the causal `key_pos <= q_pos` window exactly like the ring path's
     stale-tail garbage, which softmax turns into exact zeros. Same
     argument, same tests.

Observability (round 20): the pool itself emits nothing — page claims and
cross-pool copies during a disaggregated-prefill handoff are timed by the
ROUTER (`fleet.FleetRouter._adopt` emits the `handoff` span event with
`claim_s`/`copy_s`/`pages` into the request's trace; tpukit/obs/trace.py),
keeping this module free of telemetry plumbing: it stays a pure
allocator + layout library, and handoff cost is attributed where the
decision was made.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict, deque

import jax
import jax.numpy as jnp

from tpukit.ops import quant_comm

KV_DTYPES = ("f32", "bf16", "int8")

_STORAGE = {"f32": jnp.float32, "bf16": jnp.bfloat16}


def storage_dtype(kv_dtype: str):
    """jnp storage dtype of a non-quantized page pool."""
    if kv_dtype not in _STORAGE:
        raise ValueError(f"kv_dtype must be one of {KV_DTYPES}, got {kv_dtype!r}")
    return _STORAGE[kv_dtype]


def validate_kv_layout(cfg, page_size: int, kv_dtype: str,
                       block: int = quant_comm.DEFAULT_BLOCK) -> None:
    """Named construction-time rejection of layouts that would otherwise
    surface as opaque XLA shape errors deep inside the quantizer: int8
    pages quantize each head's flattened `[P, D]` row in `block`-element
    blocks, so the row must tile exactly."""
    if kv_dtype not in KV_DTYPES:
        raise ValueError(f"kv_dtype must be one of {KV_DTYPES}, got {kv_dtype!r}")
    if kv_dtype == "int8":
        row = page_size * cfg.head_dim
        if row % block:
            raise ValueError(
                f"kv_dtype=int8 requires the page payload per head "
                f"(page_size {page_size} x head_dim {cfg.head_dim} = {row} "
                f"elements) to be a multiple of quant_comm's {block}-element "
                f"quant block — use a page size that tiles into {block}s "
                f"(e.g. page_size {-(-block // cfg.head_dim)})"
            )


def scale_blocks(cfg, page_size: int, block: int = quant_comm.DEFAULT_BLOCK) -> int:
    """f32 scales per (page, head) row of an int8 pool."""
    return (page_size * cfg.head_dim) // block


def init_paged_cache(cfg, num_pages: int, page_size: int, pages_per_slot: int,
                     slots: int, kv_dtype: str = "f32") -> dict:
    """The paged-cache pytree the serve programs thread: K/V pools
    `[L, num_pages, H, P, D]` (int8 adds per-row scale sidecars
    `[L, num_pages, H, blocks]`) plus the block tables `[N, pages_per_slot]`
    (all zeros = every slot dereferences the null page)."""
    validate_kv_layout(cfg, page_size, kv_dtype)
    shape = (cfg.num_layers, num_pages, cfg.heads, page_size, cfg.head_dim)
    bt = jnp.zeros((slots, pages_per_slot), jnp.int32)
    if kv_dtype == "int8":
        nb = scale_blocks(cfg, page_size)
        sshape = (cfg.num_layers, num_pages, cfg.heads, nb)
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "ks": jnp.zeros(sshape, jnp.float32),
            "vs": jnp.zeros(sshape, jnp.float32),
            "bt": bt,
        }
    dt = storage_dtype(kv_dtype)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt), "bt": bt}


def pool_bytes(cfg, num_pages: int, page_size: int, kv_dtype: str) -> int:
    """Closed-form HBM bytes of the K+V pools (the equal-HBM bench math:
    int8 pays 1 byte per element plus the 4-byte-per-block f32 scale
    sidecar, i.e. `packed_bytes` per (page, head) row)."""
    per_head_row = page_size * cfg.head_dim
    if kv_dtype == "int8":
        row_bytes = quant_comm.packed_bytes(per_head_row)
    else:
        row_bytes = per_head_row * jnp.dtype(storage_dtype(kv_dtype)).itemsize
    return 2 * cfg.num_layers * num_pages * cfg.heads * row_bytes


# -- device-side page ops (called per layer from gpt.forward_cached) --------


def gather_view(pool, scales, bt, out_dtype):
    """Dereference the block tables: `pool [NP, H, P, D]` gathered through
    `bt [N, MP]` into the `[N, H, MP*P, D]` per-row K (or V) view the
    round-14 vector-cursor attention consumes. Logical position `q` of row
    `b` lives at `view[b, :, q, :]` == page `bt[b, q // P]`, offset
    `q % P` — the ONE indirection of the paged design. int8 pools
    dequantize after the gather (per-row blocks, `quant_comm` layout)."""
    v = pool[bt]  # [N, MP, H, P, D] — gather on the (unsharded) page axis
    n, mp, h, p, d = v.shape
    if scales is not None:
        # dequantize with the head axis PRESERVED (the pools shard heads
        # over `model`; merging H into a rows axis would force a GSPMD
        # reshard — the comm-free audit would break)
        s = scales[bt]  # [N, MP, H, blocks]
        v = quant_comm.dequantize_blocks(
            v.reshape(n, mp, h, p * d), s
        ).reshape(n, mp, h, p, d)
    return v.astype(out_dtype).transpose(0, 2, 1, 3, 4).reshape(n, h, mp * p, d)


def write_token(pool, scales, bt, start, val, write_mask):
    """Decode-tick write-back: row `b`'s freshly computed K (or V)
    `val [N, H, D]` lands at logical position `start[b]` — page
    `bt[b, start // P]`, offset `start % P`. Rows with `write_mask`
    False are routed to the null page (invariant 2 above): an inactive or
    prefilling slot's re-forward must never touch a real page.

    f32/bf16 pools scatter the single position; int8 pools gather the
    touched page row, dequantize, insert the exact new value, and
    REQUANTIZE the row (the block scale may move — which is why shared
    pages are never writable, invariant 1). Writable pages are exclusive
    per slot, so the scatter's row indices never collide except on the
    null page, where any winner is garbage by design."""
    n = start.shape[0]
    p = pool.shape[2]
    page = start // p
    off = start % p
    pids = jnp.take_along_axis(bt, page[:, None], axis=1)[:, 0]
    pids = jnp.where(write_mask, pids, 0)
    if scales is None:
        return pool.at[pids, :, off, :].set(val.astype(pool.dtype)), None
    h, d = pool.shape[1], pool.shape[3]
    rows = pool[pids]  # [N, H, P, D] int8
    srows = scales[pids]  # [N, H, blocks]
    # head axis preserved through the quantizer (sharding — gather_view)
    deq = quant_comm.dequantize_blocks(
        rows.reshape(n, h, p * d), srows
    ).reshape(n, h, p, d)
    hit = jax.lax.broadcasted_iota(jnp.int32, (n, 1, p, 1), 2) == off[:, None, None, None]
    deq = jnp.where(hit, val[:, :, None, :].astype(jnp.float32), deq)
    q, s = quant_comm.quantize_blocks(deq.reshape(n, h, p * d))
    return (
        pool.at[pids].set(q.reshape(n, h, p, d)),
        scales.at[pids].set(s),
    )


def write_pages(pool, scales, bt, start, vals, write_mask):
    """Prefill-chunk write-back: `vals [N, H, C, D]` covers logical
    positions `[start[b], start[b] + C)` per row, with `start` page-aligned
    and C a page multiple (the engine's chunking contract) — so the write
    is whole pages, one scatter row per (lane, chunk-page). Masked lanes
    route to the null page. Chunk positions beyond a lane's allocation
    dereference block-table zeros and also land in the null page —
    bucket-pad garbage never occupies a real page."""
    n, h, c, d = vals.shape
    p = pool.shape[2]
    npg = c // p
    first = start // p
    j = jnp.arange(npg, dtype=start.dtype)
    pids = jnp.take_along_axis(bt, first[:, None] + j[None, :], axis=1)  # [N, npg]
    pids = jnp.where(write_mask[:, None], pids, 0).reshape(-1)
    rows = (
        vals.reshape(n, h, npg, p, d)
        .transpose(0, 2, 1, 3, 4)
        .reshape(n * npg, h, p, d)
    )
    if scales is None:
        return pool.at[pids].set(rows.astype(pool.dtype)), None
    q, s = quant_comm.quantize_blocks(  # head axis preserved (sharding)
        rows.astype(jnp.float32).reshape(n * npg, h, p * d)
    )
    return (
        pool.at[pids].set(q.reshape(n * npg, h, p, d)),
        scales.at[pids].set(s),
    )


# -- page handoff (round 19, disaggregated prefill) --------------------------
# The ONE spelling of the device-to-device page copy the fleet's
# prefill->decode handoff rides (tpukit/serve/fleet.py): extract gathers the
# source pool's page rows (every layer, every head) into a dense block, the
# caller moves the block between the two engines' device subsets with ONE
# jax.device_put at the destination pool's layout, and insert scatters it
# into the destination pool. Works on K/V pools ([L, NP, H, P, D]) AND int8
# scale sidecars ([L, NP, H, blocks]) — anything with the page axis at
# position 1. `ids` is traced, so the compile count is one per padded id
# width (the caller pads to powers of two: src pads by repeating the last id
# — re-extracting a page is idempotent — and dst pads with 0, the null-page
# sink, write-safety invariant 2).


@jax.jit
def extract_pages(pool, ids):
    """`pool[:, ids]` — the page rows to hand off, `[L, n, ...]`."""
    return pool[:, ids]


@jax.jit
def insert_pages(pool, ids, block):
    """Scatter a handed-off block into `pool` at page rows `ids`. The
    destination pages are freshly allocated (exclusively owned, refcount
    1) or the null page (pad), so rows never collide with a reader."""
    return pool.at[:, ids].set(block.astype(pool.dtype))


# -- host-side page allocator + shared-prefix registry ----------------------


@dataclasses.dataclass
class PageStats:
    """Counters the engine folds into its serve windows."""

    prefix_hits: int = 0
    prefix_pages_reused: int = 0
    prefix_lookups: int = 0
    reclaimed: int = 0


class PageAllocator:
    """Host-side bookkeeping for the page pool: a free list over pages
    `1..num_pages-1` (0 is the null page), per-page refcounts, and the
    shared-prefix registry.

    The registry is a radix-style chain keyed by `(parent_page_id,
    chunk_tokens)` — a page is reachable only through its registered
    parent, so matching is exact (token tuples, no hash collisions) and a
    freed parent automatically orphans its subtree (which is purged, so a
    reallocated page id can never be matched under stale content).
    Registered pages whose refcount drops to 0 are RETAINED in an LRU and
    reclaimed only when an allocation would otherwise fail — a popular
    prefix survives gaps between requests."""

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError(
                f"num_pages={num_pages} must be >= 2 (page 0 is the "
                f"reserved null page)"
            )
        self.num_pages = num_pages
        self.page_size = page_size
        self._free = deque(range(1, num_pages))
        self.refcount = [0] * num_pages
        self._registry: dict[tuple, int] = {}  # (parent, chunk) -> page
        self._key_of: dict[int, tuple] = {}  # page -> its registry key
        self._parent: dict[int, int] = {}  # page -> parent page (0 = root)
        self._children: dict[int, set] = {}  # page -> registered children
        self._retained: OrderedDict[int, None] = OrderedDict()  # refcount-0 LRU
        self.stats = PageStats()

    # ---- accounting ----

    @property
    def free_pages(self) -> int:
        """Pages allocatable WITHOUT evicting retained prefix pages."""
        return len(self._free)

    @property
    def available_pages(self) -> int:
        """Pages an `alloc` could produce (free + reclaimable retained)."""
        return len(self._free) + len(self._retained)

    @property
    def live_pages(self) -> int:
        """Pages referenced by at least one slot."""
        return (self.num_pages - 1) - len(self._free) - len(self._retained)

    @property
    def occupancy(self) -> float:
        """Live fraction of the allocatable pool."""
        return self.live_pages / max(self.num_pages - 1, 1)

    # ---- allocate / release ----

    def alloc(self, n: int) -> list[int] | None:
        """`n` exclusive pages (refcount 1 each), or None if the pool
        cannot cover them even after reclaiming retained prefix pages
        (LRU order) — the admission-control signal. Feasibility is
        checked BEFORE any reclaim: a doomed allocation must not purge
        the retained prefix registry on its way to failing (the caller
        retries the same admission next iteration, and every hit it
        would have had is gone)."""
        if len(self._free) + len(self._retained) < n:
            return None
        while len(self._free) < n and self._retained:
            self._purge(next(iter(self._retained)))
            self.stats.reclaimed += 1
        if len(self._free) < n:
            return None
        out = [self._free.popleft() for _ in range(n)]
        for p in out:
            self.refcount[p] = 1
        return out

    def claim(self, pages: list[int]) -> None:
        """Take a reader reference on shared pages (a prefix hit). A
        retained page comes back live."""
        for p in pages:
            if p in self._retained:
                del self._retained[p]
            self.refcount[p] += 1

    def release(self, pages: list[int]) -> None:
        """Drop one reference per page (eviction). A registered page at
        refcount 0 is retained for future prefix hits; an unregistered one
        returns to the free list."""
        for p in pages:
            if p <= 0:
                continue
            self.refcount[p] -= 1
            if self.refcount[p] < 0:
                raise AssertionError(f"page {p} refcount went negative")
            if self.refcount[p] == 0:
                if p in self._key_of:
                    self._retained[p] = None
                else:
                    self._free.append(p)

    def _purge(self, pid: int) -> None:
        """Remove `pid`'s registration (and its whole registered subtree —
        children are only reachable through the parent). Retained pages in
        the subtree return to the free list; live ones just lose their
        registration and free normally at their last release."""
        key = self._key_of.pop(pid, None)
        if key is not None:
            self._registry.pop(key, None)
        parent = self._parent.pop(pid, None)
        if parent is not None and parent in self._children:
            self._children[parent].discard(pid)
        if pid in self._retained:
            del self._retained[pid]
            self._free.append(pid)
        for child in list(self._children.pop(pid, ())):
            self._purge(child)

    # ---- shared-prefix registry ----

    def _chunk(self, ids, i: int) -> tuple:
        p = self.page_size
        return tuple(int(t) for t in ids[i * p : (i + 1) * p])

    def lookup_prefix(self, ids, max_pages: int) -> list[int]:
        """Longest registered chain matching `ids` at page granularity,
        capped at `max_pages` (the caller passes `(prompt_len - 1) // P` —
        invariant 1: the page holding the last prompt position must stay
        private). Returned pages are NOT yet claimed."""
        self.stats.prefix_lookups += 1
        out: list[int] = []
        parent = 0
        for i in range(max_pages):
            pid = self._registry.get((parent, self._chunk(ids, i)))
            if pid is None:
                break
            out.append(pid)
            parent = pid
        return out

    def register(self, ids, pages: list[int]) -> None:
        """Publish `pages[i] = K/V of ids[i*P:(i+1)*P]` into the registry
        (called once a slot's prefill completes — the pages are final and
        read-only from here on). Already-registered chunks keep their
        first registration; our duplicate page stays private and frees
        normally, while deeper chunks chain from the canonical page so one
        popular prefix converges to one chain."""
        parent = 0
        for i, pid in enumerate(pages):
            key = (parent, self._chunk(ids, i))
            existing = self._registry.get(key)
            if existing is not None:
                parent = existing
                continue
            if pid in self._key_of:  # already published under another chain
                parent = pid
                continue
            self._registry[key] = pid
            self._key_of[pid] = key
            self._parent[pid] = parent
            self._children.setdefault(parent, set()).add(pid)
            parent = pid

    def registered_pages(self) -> int:
        return len(self._key_of)
