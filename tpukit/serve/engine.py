"""Continuous-batching serving engine: the host-side slot scheduler.

Round 14 (ROADMAP #1): the "millions of users" half of the north star.
The device programs live in `tpukit/serve/decode.py`; this module owns
everything around them — admission, eviction, the request stream, and
the serving telemetry — in the shape real TPU serving engines take:

  - A **slot ring**: `slots` decode lanes over one preallocated KV ring
    (`gpt.init_kv_cache(cfg, slots, width)`). A free-list (ring order)
    assigns arriving requests to lanes; eviction on EOS/length returns
    the lane, and the next prefill alone makes it safe to reuse (stale
    cache garbage above the new cursor is never attended — decode.py).
  - **Prefill/decode phase separation**: arrivals are admitted BETWEEN
    decode quanta via `prefill_slots`, which touches only the free
    lanes — active slots never stall on an arriving prompt. Prompts pad
    to a small declared set of length buckets and admit-batches pad to
    powers of two, so the serve path compiles at most
    `ServeConfig.compile_budget` programs (asserted in
    tests/test_serve.py).
  - **Continuous decode**: one `decode_step` advances every active lane
    one token; the per-step host sync is one `[N]` cursor/flag fetch —
    the EOS-detection cost every host-scheduled engine pays.
  - **Serving telemetry** through the SAME stack that covers training
    (spans -> JSONL -> flight recorder -> tools/report.py): per-window
    `kind="serve"` records (tokens/s, occupancy, admit/evict counts,
    prefill/decode/sync wall split + explicit `other_s` residual,
    per-window dispatch-vs-device attribution, per-token + end-to-end
    latency percentiles) and one final `kind="serve_summary"`. With a
    `tracer` (round 20, tpukit/obs/trace.py) the step primitives also
    emit per-request span events — enqueue/admit/prefill/quantum/finish
    — merged into span trees with per-phase p50/p99 in the summary.

Sharded serving: pass `mesh` (and params placed at their training
shardings) and the engine places the KV ring `[L, N, H, W, D]` as
`P(None, "data", "model", None, None)` — slots data-parallel, heads
tensor-parallel — with the per-slot host state sharded over `data`.
The decode step's per-step collectives then match the closed form
`decode.decode_step_comm` (audited against compiled HLO in tests).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from tpukit.model import gpt
from tpukit.obs import SpanTimeline
from tpukit.obs import metrics as metrics_lib
from tpukit.obs import trace as trace_lib
from tpukit.serve import decode as serve_decode


@dataclasses.dataclass(frozen=True)
class Request:
    """One inference request: a tokenized prompt plus its decode budget.
    `arrival_s` is the offset (seconds, stream-relative) at which the
    request becomes visible to the scheduler — 0 for an offered-up-front
    batch, spaced for an arrival process. `trace` is the request's trace
    id (round 20, tpukit/obs/trace.py); -1 defaults it to the rid. A
    requeued-after-kill attempt reuses the SAME Request, so both
    attempts share one trace id by construction. `deadline_ms` (round 24)
    is an end-to-end latency bound measured from `arrival_s`: 0 disables
    it, >0 makes the engine EVICT the request once exceeded (reason
    \"deadline\", partial output kept). `priority` orders backpressure
    shedding in the fleet router — lower sheds first; it never reorders
    admission (FIFO within the arrived set is the latency contract)."""

    rid: int
    ids: tuple[int, ...]
    max_new_tokens: int = 20
    seed: int = 0
    arrival_s: float = 0.0
    trace: int = -1
    deadline_ms: float = 0.0
    priority: int = 0


def trace_id(req: Request) -> int:
    """Effective trace id (trace_lib.request_trace_id over a Request)."""
    return req.trace if req.trace >= 0 else req.rid


@dataclasses.dataclass
class Completion:
    """A finished request. `ids` holds prompt + generated tokens;
    timestamps are engine-clock seconds (run-relative). The paged fields
    (round 15) are 0/absent under the ring cache: `pages` is the request's
    page footprint, `prefix_pages` how many of them were shared-prefix
    hits, and `active_s` when its prefill finished and decode began
    (== `admit_s` for the ring's one-shot prefill)."""

    rid: int
    ids: np.ndarray
    prompt_len: int
    generated: int
    reason: str  # "eos" | "length" | "deadline"
    arrival_s: float
    admit_s: float
    done_s: float
    pages: int = 0
    prefix_pages: int = 0
    active_s: float = 0.0

    @property
    def admit_latency_s(self) -> float:
        """Slot-assignment to decode-ready: the prefill cost a request
        actually paid — what shared-prefix reuse shrinks."""
        return max(self.active_s - self.admit_s, 0.0)

    @property
    def e2e_s(self) -> float:
        """End-to-end latency including queue wait — what a user sees."""
        return self.done_s - self.arrival_s

    @property
    def per_token_s(self) -> float:
        """Decode-resident seconds per generated token."""
        return (self.done_s - self.admit_s) / max(self.generated, 1)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine shape. `buckets` is the DECLARED prompt-length set — the
    whole compile budget of the serve path (one prefill program per
    bucket + one decode step). Prompts longer than `max(buckets)` are
    rejected at admission (callers truncate upstream, the reference's
    own prompt contract). The KV ring width is
    `max(buckets) + max_new_tokens` unless `max_len` pins it."""

    slots: int = 8
    buckets: tuple[int, ...] = (16, 32, 64)
    max_new_tokens: int = 20
    temperature: float = 0.0
    top_k: int = 0
    window_steps: int = 32  # decode steps per kind="serve" JSONL window
    max_len: int = 0
    # Decode QUANTUM: tokens decoded per runtime dispatch (and per host
    # sync). 1 = per-token scheduling (tightest admit/evict latency);
    # larger amortizes the per-dispatch host overhead that otherwise
    # dominates small-model decode (decode.decode_step docstring). Token
    # streams are identical at any quantum — finished slots freeze
    # mid-quantum — only latency granularity changes.
    decode_quantum: int = 4
    # Paged KV (round 15, ROADMAP #2). 0 = the round-14 per-slot ring
    # (byte-identical behavior). > 0 = fixed-size pages of this many token
    # positions + per-slot block tables (serve/paged.py): requests hold
    # ceil(min(prompt+budget, width)/page_size) pages instead of a
    # full-width slot, prompt prefixes are shared page-granular across
    # requests, and prefill runs CHUNKED between decode quanta. Page size
    # must divide every bucket so admit chunks stay page-aligned.
    page_size: int = 0
    # Page-pool size; 0 derives the ring-equivalent pool (slots x
    # pages-per-slot + the null page) — same KV HBM, so the paged win
    # reads as footprint, not as a bigger budget. The bench shrinks/grows
    # it explicitly for the equal-HBM comparison.
    num_pages: int = 0
    # Page payload storage: "f32"/"bf16" store that dtype (token-exact
    # when it equals the compute dtype); "int8" block-quantizes page rows
    # with quant_comm's 256-element-block quantizer for ~4x pages per HBM
    # byte — lossy, gated by a token-level tolerance test, never claimed
    # token-exact. Non-f32 requires the paged cache.
    kv_dtype: str = "f32"
    # Chunked-prefill chunk (tokens per prefill dispatch, page multiple);
    # 0 = one page per chunk. A lane advances one chunk per scheduler
    # iteration with decode quanta in between, so a long prompt can never
    # stall active slots for more than one chunk's compute.
    prefill_chunk: int = 0
    # Speculative decoding (round 17, ROADMAP #3; tpukit/serve/spec.py).
    # "" = vanilla decode quanta. "ngram" = self-speculation: prompt-
    # lookup drafting from each slot's own history, no second model.
    # "model" = a small tpukit GPT draft model (pass draft_params /
    # draft_cfg to the engine). Either way the target scores all
    # spec_k + 1 positions in ONE batched forward and rejection sampling
    # keeps the output distribution EXACT: greedy output is token-
    # identical to vanilla decode, sampled output is an exact target-
    # distribution sample (spec.py module docstring). Requires the ring
    # cache (page_size == 0): the multi-token verify write-back does not
    # fit the paged whole-page write contract this round.
    draft: str = ""  # "" | "ngram" | "model"
    # Draft tokens proposed per slot per quantum (the verify window is
    # spec_k + 1 wide). The KV ring over-allocates this many scratch
    # positions past `width` so a lane near its limit still writes a full
    # verify window without update-slice clamping (spec.py docstring).
    spec_k: int = 4
    # Longest n-gram the self-speculation proposer matches (it falls back
    # through shorter suffixes down to 1).
    ngram_max: int = 3
    # Fused paged decode + on-device scheduler loop (round 21, ROADMAP
    # #3). False (default): byte-identical engine behavior — the unfused
    # per-quantum decode_step. True (paged only): T==1 attention runs
    # the fused Pallas kernel (tpukit/ops/paged_attention.py — block
    # tables dereferenced in-kernel, no per-layer gather) and each
    # quantum dispatches decode.decode_loop_window — scheduler state
    # (cursors, EOS/limit flags, the freed-page account) lives on device
    # across up to `decode_quantum` ticks with early exit when every
    # lane finishes or enough pages free to admit the head-of-queue
    # request. Token streams are identical either way; only the kernel
    # and the host sync cadence change.
    fused_decode: bool = False

    def __post_init__(self):
        if self.draft not in ("", "ngram", "model"):
            raise ValueError(
                f"draft={self.draft!r} must be '', 'ngram' or 'model'"
            )
        if self.draft:
            if self.spec_k < 1:
                raise ValueError(
                    f"spec_k={self.spec_k} must be >= 1 with draft="
                    f"{self.draft!r} — a 0-token draft is vanilla decode"
                )
            if self.ngram_max < 1:
                raise ValueError(f"ngram_max={self.ngram_max} must be >= 1")
            if self.page_size:
                raise ValueError(
                    f"draft={self.draft!r} requires the ring cache "
                    f"(page_size=0, got {self.page_size}): the k+1-token "
                    f"verify write-back is not page-aligned, and the paged "
                    f"write contract only covers whole pages — speculative "
                    f"+ paged is a future round (DESIGN.md §16)"
                )
        if self.slots < 1:
            raise ValueError(f"slots={self.slots} must be >= 1")
        if self.decode_quantum < 1:
            raise ValueError(
                f"decode_quantum={self.decode_quantum} must be >= 1"
            )
        b = tuple(self.buckets)
        if not b or list(b) != sorted(set(b)) or b[0] < 1:
            raise ValueError(
                f"buckets={self.buckets} must be unique, ascending and >= 1 "
                f"— the bucket set IS the declared compile budget"
            )
        if self.max_new_tokens < 1:
            raise ValueError(f"max_new_tokens={self.max_new_tokens} must be >= 1")
        if self.max_len and self.max_len < max(b):
            raise ValueError(
                f"max_len={self.max_len} is smaller than the largest bucket "
                f"({max(b)}) — a prompt admitted at that bucket could not fit "
                f"the KV ring (it would crash at prefill, not here)"
            )
        from tpukit.serve import paged as paged_lib

        if self.kv_dtype not in paged_lib.KV_DTYPES:
            raise ValueError(
                f"kv_dtype must be one of {paged_lib.KV_DTYPES}, "
                f"got {self.kv_dtype!r}"
            )
        if self.page_size < 0:
            raise ValueError(f"page_size={self.page_size} must be >= 0")
        if self.page_size == 0:
            if self.fused_decode:
                raise ValueError(
                    "fused_decode=True requires the paged cache "
                    "(page_size > 0) — the fused kernel walks block "
                    "tables; the ring path keeps its round-14 trace"
                )
            if self.kv_dtype != "f32":
                raise ValueError(
                    f"kv_dtype={self.kv_dtype!r} requires the paged cache "
                    f"(page_size > 0) — the ring stores the compute dtype"
                )
            for name in ("num_pages", "prefill_chunk"):
                if getattr(self, name):
                    raise ValueError(
                        f"{name}={getattr(self, name)} requires the paged "
                        f"cache (page_size > 0)"
                    )
            return
        bad = [x for x in b if x % self.page_size]
        if bad:
            raise ValueError(
                f"page_size={self.page_size} must divide every bucket "
                f"width (buckets {bad} don't tile) — admit chunks are "
                f"page-aligned whole-page writes"
            )
        if self.prefill_chunk and self.prefill_chunk % self.page_size:
            raise ValueError(
                f"prefill_chunk={self.prefill_chunk} must be a multiple of "
                f"page_size={self.page_size} — chunks write whole pages"
            )
        if self.prefill_chunk:
            bad = [x for x in b if x % self.prefill_chunk]
            if bad:
                raise ValueError(
                    f"prefill_chunk={self.prefill_chunk} must divide every "
                    f"bucket width (buckets {bad} don't tile) — a partial "
                    f"tail chunk would write past its bucket row"
                )
        if self.num_pages and self.num_pages - 1 < self.pages_per_slot:
            raise ValueError(
                f"num_pages={self.num_pages} cannot hold even one "
                f"worst-case request ({self.pages_per_slot} pages for "
                f"width {self.width}, plus the reserved null page)"
            )

    @property
    def width(self) -> int:
        return self.max_len or (max(self.buckets) + self.max_new_tokens)

    @property
    def paged(self) -> bool:
        return self.page_size > 0

    @property
    def pages_per_slot(self) -> int:
        """Block-table width: pages covering the worst-case logical
        sequence. Only meaningful when paged."""
        return -(-self.width // self.page_size)

    @property
    def padded_width(self) -> int:
        """Logical per-slot width of the paged view (width rounded up to
        whole pages); == `width` for the ring."""
        return self.pages_per_slot * self.page_size if self.paged else self.width

    @property
    def chunk(self) -> int:
        """Chunked-prefill chunk actually used (paged only)."""
        return self.prefill_chunk or self.page_size

    @property
    def kv_width(self) -> int:
        """Physical KV-ring width: the logical width plus the spec-decode
        scratch tail (`spec_k` positions a verify window near the buffer
        end spills into — never appended, never attended; spec.py)."""
        return self.padded_width + (self.spec_k if self.draft else 0)

    @property
    def compile_budget(self) -> int:
        """Declared ceiling on serve-path compiles: ONE decode program
        (at this quantum) plus one prefill program per admit size — the
        admit batcher pads group sizes to powers of two precisely so this
        stays a small static set (asserted in tests). Ring prefills
        compile per (bucket, admit size); paged chunked prefills have ONE
        static chunk width, so only the admit sizes multiply.

        Speculative decoding swaps the decode program for ONE verify
        program; the "model" draft adds one draft-propose loop and a
        second prefill program per (bucket, admit size) — the draft ring
        is prefilled by the same batched program as the target's."""
        admit_sizes = (self.slots - 1).bit_length() + 1
        if self.paged:
            return 1 + admit_sizes
        prefills = len(self.buckets) * admit_sizes
        if self.draft == "model":
            return 2 + 2 * prefills
        return 1 + prefills


@dataclasses.dataclass
class _Lane:
    req: Request
    admit_s: float
    prompt_len: int
    bucket: int
    # paged-only state (round 15): the lane's page footprint (shared
    # prefix first, then private pages), how many lead pages are shared
    # read-only hits, the chunked-prefill cursor (next chunk start; the
    # lane is decoding once it reaches `prefill_end`), and when decode
    # became ready.
    pages: list[int] = dataclasses.field(default_factory=list)
    shared: int = 0
    next_chunk: int = 0
    prefill_end: int = 0
    phase: str = "decode"  # "prefill" until the last chunk is dispatched
    active_s: float = 0.0
    # per-request PRNG key bytes, computed ONCE at admission — chunk
    # dispatches must not pay a device round-trip per lane per iteration
    key: np.ndarray | None = None


def _pct(vals, q) -> float | None:
    return float(np.percentile(np.asarray(vals), q)) if vals else None


class ServeEngine:
    """Host-side continuous-batching loop over the decode.py programs.

    `params` must already sit at the caller's serving shardings (the
    training shardings under a TP mesh, or any single-device/replicated
    layout); the engine never moves them. `logger`/`recorder` take the
    trainer's StepLogger / FlightRecorder — pass None for silent runs.
    """

    def __init__(self, params, cfg: gpt.GPTConfig, serve: ServeConfig,
                 eos_id: int, mesh=None, logger=None, recorder=None,
                 draft_params=None, draft_cfg=None, replica=None,
                 tracer=None, metrics=None, slo=None, metrics_dir=None):
        if serve.kv_width > cfg.max_position_embeddings:
            raise ValueError(
                f"KV ring width {serve.kv_width} (max bucket "
                f"{max(serve.buckets)} + max_new_tokens "
                f"{serve.max_new_tokens}"
                + (f" + spec_k {serve.spec_k} verify scratch"
                   if serve.draft else "")
                + f") exceeds the position table "
                f"({cfg.max_position_embeddings}) — beyond it position "
                f"lookups silently clamp instead of erroring"
            )
        if serve.draft == "model":
            if draft_params is None or draft_cfg is None:
                raise ValueError(
                    "draft='model' requires draft_params and draft_cfg "
                    "(a tpukit GPT draft — restore one via "
                    "checkpoint.restore_params, main-serve.py "
                    "--draft_checkpoint)"
                )
            # Named at construction, not a shape error at the first
            # verify: the acceptance test compares p and q elementwise
            # over the logits axis, so the draft must speak the TARGET's
            # token ids — same tokenizer vocab AND the same padded width.
            if (draft_cfg.vocab_size != cfg.vocab_size
                    or draft_cfg.padded_vocab_size != cfg.padded_vocab_size):
                raise ValueError(
                    f"draft model vocab (vocab_size "
                    f"{draft_cfg.vocab_size}, padded "
                    f"{draft_cfg.padded_vocab_size}) does not match the "
                    f"target ({cfg.vocab_size}, padded "
                    f"{cfg.padded_vocab_size}) — draft and target must "
                    f"share one tokenizer; the rejection-sampling "
                    f"correction compares their distributions token id "
                    f"by token id"
                )
            if serve.kv_width > draft_cfg.max_position_embeddings:
                raise ValueError(
                    f"draft model position table "
                    f"({draft_cfg.max_position_embeddings}) is smaller "
                    f"than the KV ring width {serve.kv_width} — the "
                    f"draft decodes the same positions the target serves"
                )
        elif draft_params is not None or draft_cfg is not None:
            raise ValueError(
                f"draft_params/draft_cfg passed but ServeConfig.draft="
                f"{serve.draft!r} — set draft='model' to use them"
            )
        self.params = params
        # round 21: --fused_decode flips the MODEL flag too — the decode
        # step's T==1 paged attention routes through the fused kernel.
        # Off keeps cfg untouched, so traces are byte-identical.
        self.cfg = cfg.replace(fused_decode=True) if serve.fused_decode else cfg
        self.serve = serve
        self.eos_id = int(eos_id)
        self.mesh = mesh
        self.logger = logger
        self.recorder = recorder
        # Fleet identity (round 19, tpukit/serve/fleet.py): stamped on
        # every serve window/summary this engine emits so the fleet report
        # can aggregate per-replica telemetry. None = standalone engine,
        # records unchanged.
        self.replica = replica
        # Request-scoped tracing (round 20, tpukit/obs/trace.py): a
        # shared TraceRecorder the step primitives emit span events into.
        # None = tracing off — every tracer touch below is gated so the
        # token stream and schedule are bit-identical either way
        # (asserted in tests/test_trace.py).
        self.tracer = tracer
        # Metrics plane (round 22, tpukit/obs/metrics.py): a shared
        # MetricRegistry observed at WINDOW boundaries only — every
        # histogram is DERIVED from the completions / trace trees /
        # quantum events the engine already produces, so the step
        # primitives and the token stream are bit-identical with
        # metrics on or off (asserted in tests/test_metrics.py).
        # `slo` is a list of parsed SloTargets (metrics_lib.parse_slo);
        # a fleet passes slo=None to its replicas and accounts SLOs at
        # the router, mirroring the shared-tracer flush discipline.
        self.metrics = metrics
        self.slo_accountant = (
            metrics_lib.SloAccountant(slo)
            if (metrics is not None and slo) else None
        )
        self.metrics_dir = metrics_dir
        self._metrics_traces_seen: set = set()
        self._metrics_q_mark = -1.0  # quantum watermark (t1 run-clock)
        self._pending_quantum = None  # dispatch half of the quantum event
        # fused windows (round 21): the device tick counter of the last
        # decode_loop_window dispatch, fetched at the window-boundary sync
        # (the loop may exit early, so the host can't assume the quantum)
        self._pending_ticks = None
        self.draft_params = draft_params
        self.draft_cfg = draft_cfg
        # lax.top_k rejects k beyond the logits width — clamp like generate()
        self._top_k = min(int(serve.top_k), cfg.padded_vocab_size)
        n, w = serve.slots, serve.padded_width

        if serve.paged:
            from tpukit.serve import paged as paged_lib

            # named at construction, not an XLA shape error at first write
            paged_lib.validate_kv_layout(cfg, serve.page_size, serve.kv_dtype)
        if mesh is not None:
            from tpukit.mesh import place_host_array

            if jax.process_count() > 1:
                raise NotImplementedError(
                    "ServeEngine schedules from ONE host (per-quantum "
                    "cursor fetches via device_get are not legal on "
                    "cross-host sharded arrays) — run one engine per host "
                    "over that host's devices; cross-host serving is a "
                    "future round"
                )
            d = mesh.shape.get("data", 1)
            if serve.paged and d > 1:
                raise ValueError(
                    f"paged serving requires a model-only grid (data axis "
                    f"1, got data={d}): the page pool is replicated across "
                    f"`data` and a data-sharded slot set would make the "
                    f"pool write-back an unauditable cross-shard scatter "
                    f"(decode.decode_step_comm) — shrink the data axis or "
                    f"use the ring cache (page_size=0)"
                )
            if n % d:
                raise ValueError(
                    f"slots={n} must be a multiple of the mesh's data axis "
                    f"({d}) — slots shard over it"
                )
            m = mesh.shape.get("model", 1)
            heads_ax = "model" if (m > 1 and cfg.heads % m == 0) else None
            batch_ax = "data" if d > 1 else None
            # place_host_array: multi-host safe (every process calls with
            # the same value; single-process is a plain device_put)
            place = lambda x, spec: place_host_array(
                np.asarray(x), NamedSharding(mesh, spec)
            )
            cache_spec = P(None, batch_ax, heads_ax, None, None)
            pool_spec = P(None, None, heads_ax, None, None)
            scale_spec = P(None, None, heads_ax, None)
            slot_spec = P(batch_ax)
        else:
            place = lambda x, spec: jnp.asarray(x)
            cache_spec = pool_spec = scale_spec = slot_spec = P()
        self._place = place
        # kept for the fleet page handoff: a copied page block lands at the
        # destination pool's layout (fleet._copy_pages, round 19)
        self._pool_spec = pool_spec
        self._scale_spec = scale_spec

        self.buf = place(np.zeros((n, w), np.int32), P(*slot_spec, None))
        if serve.paged:
            self.num_pages = serve.num_pages or n * serve.pages_per_slot + 1
            tree = paged_lib.init_paged_cache(
                cfg, self.num_pages, serve.page_size, serve.pages_per_slot,
                n, serve.kv_dtype,
            )
            specs = {"k": pool_spec, "v": pool_spec, "ks": scale_spec,
                     "vs": scale_spec, "bt": P()}
            self.cache = {key: place(val, specs[key]) for key, val in tree.items()}
            self.allocator = paged_lib.PageAllocator(
                self.num_pages, serve.page_size
            )
            self.kv_bytes = paged_lib.pool_bytes(
                cfg, self.num_pages, serve.page_size, serve.kv_dtype
            )
            self._bt = np.zeros((n, serve.pages_per_slot), np.int32)
            self._bt_dirty = False
        else:
            self.num_pages = 0
            self.allocator = None
            ring = gpt.init_kv_cache(cfg, n, serve.kv_width)
            self.kv_bytes = sum(
                int(np.prod(c.shape)) * c.dtype.itemsize for c in ring.values()
            )
            self.cache = jax.tree.map(lambda c: place(c, cache_spec), ring)
        self._slot_spec = slot_spec
        self.draft_cache = None
        if serve.draft == "model":
            # the draft's own ring, same slots/width discipline as the
            # target's; REPLICATED under a mesh (the draft is small — its
            # forward is not the audited program, and replication keeps
            # any head count legal whatever the model axis)
            self.draft_cache = jax.tree.map(
                lambda c: place(c, P()),
                gpt.init_kv_cache(draft_cfg, n, serve.kv_width),
            )
        # spec telemetry (round 17): proposed/accepted draft tokens, the
        # appended-tokens-per-verify histogram (index 0..spec_k+1), and
        # the host-side snapshot pending the next sync drain
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_hist = [0] * (serve.spec_k + 2) if serve.draft else []
        self._pending_spec = None
        self.cursors = place(np.zeros((n,), np.int32), slot_spec)
        self.active = place(np.zeros((n,), bool), slot_spec)
        self.limits = place(np.zeros((n,), np.int32), slot_spec)
        self.keys = place(np.zeros((n, 2), np.uint32), P(*slot_spec, None))

        self._free = deque(range(n))
        self._lanes: dict[int, _Lane] = {}
        self._pending: deque[Request] = deque()
        self.completions: list[Completion] = []
        self.spans = SpanTimeline()
        self.buckets_used: set[int] = set()
        self.steps = 0
        self.admitted = 0
        self.max_live = 0
        self.evicted = {"eos": 0, "length": 0, "deadline": 0}
        # rids pinned past natural retirement (stuck_request@RID chaos,
        # round 24): _sync_evict refuses to retire them so the lane holds
        # its slot until deadline_ms eviction reclaims it — pure host-side
        # control plane, the compiled decode step is untouched
        self.stuck_rids: set[int] = set()
        self._gen_total = 0
        self.last_summary: dict | None = None
        # per-window deltas
        self._win = dict(steps=0, gen0=0, admit0=0, comps0=0, hits0=0,
                         prop0=0, acc0=0, hist0=list(self.spec_hist))
        self._window_idx = 0

    # ---- scheduling ------------------------------------------------------

    def bucket_for(self, prompt_len: int) -> int:
        """Smallest declared bucket that fits the prompt; admission-time
        rejection for prompts beyond the largest bucket keeps the compile
        budget exactly the declared set."""
        for b in self.serve.buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt of {prompt_len} tokens exceeds the largest declared "
            f"bucket ({max(self.serve.buckets)}) — truncate upstream or "
            f"declare a larger bucket"
        )

    def _admit_batch(self, reqs: list[Request], now: float) -> None:
        """Admit up to `len(self._free)` arrived requests: group by bucket
        and prefill each group in ONE `prefill_slots` dispatch (one
        batched forward for the whole group — per-request prefill calls
        would pay the per-dispatch host overhead A times). Each group's
        admit-batch is padded to the next power of two by REPEATING the
        last entry (a repeated admit rewrites the same slot with the same
        values — idempotent), so prefill compiles stay bounded by
        buckets x admit sizes (`ServeConfig.compile_budget`)."""
        # Validate EVERY request before popping any slot: a mid-batch raise
        # after partial pops would leak lanes out of the free list and drop
        # the already-popped requests from both queues.
        validated = []
        for req in reqs:
            prompt_len = len(req.ids)
            if prompt_len < 1:
                raise ValueError(f"request {req.rid}: empty prompt")
            validated.append((req, prompt_len, self.bucket_for(prompt_len)))
        groups: dict[int, list[tuple[int, Request, int]]] = {}
        for req, prompt_len, bucket in validated:
            groups.setdefault(bucket, []).append(
                (self._free.popleft(), req, prompt_len)
            )
        tr = self.tracer
        for bucket, entries in sorted(groups.items()):
            a = 1 << (len(entries) - 1).bit_length()  # pad to power of two
            rows = np.zeros((a, bucket), np.int32)
            slots = np.zeros((a,), np.int32)
            plens = np.zeros((a,), np.int32)
            lims = np.zeros((a,), np.int32)
            keys = np.zeros((a, 2), np.uint32)
            for i in range(a):
                slot, req, plen = entries[min(i, len(entries) - 1)]
                rows[i, :plen] = req.ids
                slots[i], plens[i] = slot, plen
                lims[i] = min(plen + req.max_new_tokens, self.serve.width)
                keys[i] = np.asarray(jax.random.PRNGKey(req.seed), np.uint32)
            p0 = tr.now() if tr is not None else 0.0
            with self.spans.span("prefill"):
                (self.buf, self.cache, self.cursors, self.active, self.limits,
                 self.keys) = serve_decode.prefill_slots(
                    self.params, self.cfg, self.buf, self.cache, self.cursors,
                    self.active, self.limits, self.keys,
                    self._place(slots, P()), self._place(rows, P()),
                    self._place(plens, P()), self._place(lims, P()),
                    self._place(keys, P()),
                )
                if self.serve.draft == "model":
                    # prefill the DRAFT ring for the same admit batch —
                    # the same batched program against the draft's
                    # params/cache; the non-cache outputs are identical
                    # values to the target call's and are discarded
                    _, self.draft_cache, *_ = serve_decode.prefill_slots(
                        self.draft_params, self.draft_cfg, self.buf,
                        self.draft_cache, self.cursors, self.active,
                        self.limits, self.keys,
                        self._place(slots, P()), self._place(rows, P()),
                        self._place(plens, P()), self._place(lims, P()),
                        self._place(keys, P()),
                    )
            self.buckets_used.add(bucket)
            p1 = tr.now() if tr is not None else 0.0
            for slot, req, plen in entries:
                self._lanes[slot] = _Lane(req, now, plen, bucket, active_s=now)
                self.admitted += 1
                if tr is not None:
                    tid = trace_id(req)
                    tr.emit("admit", tid, rid=req.rid, t=now, slot=slot,
                            replica=self.replica)
                    tr.emit("prefill", tid, rid=req.rid, t0=p0, t1=p1,
                            chunk=0, replica=self.replica)
                    tr.emit("prefill_done", tid, rid=req.rid, t=p1,
                            replica=self.replica)
        self.max_live = max(self.max_live, len(self._lanes))

    # ---- paged scheduling (round 15) -------------------------------------

    def _admit_paged_one(self, req: Request, now: float) -> bool:
        """Admit one request into the paged pool, or return False when the
        pool cannot cover it yet (head-of-line admission control — pages,
        not just lanes, are the capacity). The request's whole worst case
        — `ceil(min(prompt + budget, width) / P)` pages — is allocated up
        front, so decode can never starve mid-request; the savings vs the
        ring is the footprint (actual need, not bucket width), plus every
        shared-prefix page the registry already holds.

        Prefix reuse: the registry walk is capped at `(prompt_len-1) // P`
        (the last prompt position's page must stay private — it is
        rewritten by the first decode tick) and aligned DOWN to the
        prefill chunk so the remaining suffix starts on a chunk boundary.
        Shared pages are claimed (refcounted) before the private
        allocation so the allocator's retained-LRU reclaim can't steal
        them in between."""
        plen = len(req.ids)
        if plen < 1:
            raise ValueError(f"request {req.rid}: empty prompt")
        bucket = self.bucket_for(plen)
        p, c = self.serve.page_size, self.serve.chunk
        limit = min(plen + req.max_new_tokens, self.serve.width)
        total = -(-limit // p)
        matched = self.allocator.lookup_prefix(req.ids, (plen - 1) // p)
        s_tokens = (len(matched) * p // c) * c
        shared = matched[: s_tokens // p]
        self.allocator.claim(shared)
        fresh = self.allocator.alloc(total - len(shared))
        if fresh is None:
            self.allocator.release(shared)
            return False
        slot = self._free.popleft()
        pages = list(shared) + fresh
        self._bt[slot] = 0
        self._bt[slot, : len(pages)] = pages
        self._bt_dirty = True
        # prefill only the chunks that hold prompt tokens — the ring
        # prefilled the whole bucket, but bucket-pad K/V is causally dead
        # (never attended), so chunks past ceil(plen/chunk) would be pure
        # padding forwards that delay decode arming and inflate admit
        # latency. Position plen-1 always lands in the last dispatched
        # chunk (s_tokens <= ((plen-1)//p)*p < plen <= prefill_end).
        prefill_end = -(-plen // c) * c
        self._lanes[slot] = _Lane(
            req, now, plen, bucket, pages=pages, shared=len(shared),
            next_chunk=s_tokens, prefill_end=prefill_end, phase="prefill",
            key=np.asarray(jax.random.PRNGKey(req.seed), np.uint32),
        )
        self.admitted += 1
        self.max_live = max(self.max_live, len(self._lanes))
        self.buckets_used.add(bucket)
        if self.tracer is not None:
            self.tracer.emit("admit", trace_id(req), rid=req.rid, t=now,
                             slot=slot, replica=self.replica)
        if shared:
            self.allocator.stats.prefix_hits += 1
            self.allocator.stats.prefix_pages_reused += len(shared)
        return True

    def _dispatch_prefill_chunks(self, now: float) -> None:
        """Advance every prefilling lane by ONE chunk in one batched
        dispatch (`decode.prefill_chunk_paged`), interleaved with decode
        quanta by the run loop — the chunked-prefill contract: a long
        prompt costs active slots at most one chunk of compute per
        scheduler iteration, and a prefix-hit admission starts at its
        first UNSHARED chunk (a full-prefix hit dispatches only the final
        chunk holding the private last-prompt page). Lanes finishing
        their last chunk arm decode state on-device and are registered
        into the prefix registry here (host metadata; device ordering
        guarantees the chunk's writes land before any later read)."""
        entries = []
        c = self.serve.chunk
        for slot, lane in self._lanes.items():
            if lane.phase != "prefill":
                continue
            start = lane.next_chunk
            seg = lane.req.ids[start : start + c]
            row = np.zeros((c,), np.int32)
            row[: len(seg)] = seg
            entries.append((slot, lane, start, row, start + c >= lane.prefill_end))
        if not entries:
            return
        a = 1 << (len(entries) - 1).bit_length()  # pad to power of two
        rows = np.zeros((a, c), np.int32)
        slots = np.zeros((a,), np.int32)
        starts = np.zeros((a,), np.int32)
        last = np.zeros((a,), bool)
        plens = np.zeros((a,), np.int32)
        lims = np.zeros((a,), np.int32)
        keys = np.zeros((a, 2), np.uint32)
        for i in range(a):  # repeats are idempotent (round-14 admit trick)
            slot, lane, start, row, is_last = entries[min(i, len(entries) - 1)]
            rows[i], slots[i], starts[i], last[i] = row, slot, start, is_last
            plens[i] = lane.prompt_len
            lims[i] = min(lane.prompt_len + lane.req.max_new_tokens,
                          self.serve.width)
            keys[i] = lane.key
        self._refresh_bt()
        tr = self.tracer
        p0 = tr.now() if tr is not None else 0.0
        with self.spans.span("prefill"):
            (self.buf, self.cache, self.cursors, self.active, self.limits,
             self.keys) = serve_decode.prefill_chunk_paged(
                self.params, self.cfg, self.buf, self.cache, self.cursors,
                self.active, self.limits, self.keys,
                self._place(slots, P()), self._place(rows, P()),
                self._place(starts, P()), self._place(last, P()),
                self._place(plens, P()), self._place(lims, P()),
                self._place(keys, P()),
            )
        p1 = tr.now() if tr is not None else 0.0
        for slot, lane, start, row, is_last in entries:
            lane.next_chunk = start + c
            if tr is not None:
                tid = trace_id(lane.req)
                tr.emit("prefill", tid, rid=lane.req.rid, t0=p0, t1=p1,
                        chunk=start // c, replica=self.replica)
                if is_last:
                    tr.emit("prefill_done", tid, rid=lane.req.rid, t=p1,
                            replica=self.replica)
            if is_last:
                lane.phase = "decode"
                lane.active_s = now
                reg = (lane.prompt_len - 1) // self.serve.page_size
                self.allocator.register(lane.req.ids, lane.pages[:reg])

    def _refresh_bt(self) -> None:
        """Push the host block tables to the device copy the programs
        read. Tables change only at admission/eviction; between those the
        cached device array rides along unchanged through every jit."""
        if self._bt_dirty:
            self.cache["bt"] = self._place(self._bt, P())
            self._bt_dirty = False

    def _step(self) -> None:
        if self.serve.paged:
            self._refresh_bt()
        tr = self.tracer
        t0 = tr.now() if tr is not None else 0.0
        if self.serve.fused_decode:
            # round 21: the whole quantum runs as ONE on-device
            # while_loop dispatch (decode.decode_loop_window) — cursors,
            # EOS/limit flags and the freed-page account advance on
            # device, and the loop hands back early when every lane is
            # done or finished lanes have freed enough pages to admit
            # the head of the queue (its worst-case footprint; 1<<30
            # disables the exit when nothing is waiting — a spurious
            # early exit only costs one extra host round-trip, so the
            # conservative target is safe). The tick count is a DEVICE
            # scalar; `_sync_evict` fetches it with the cursors and
            # accounts steps there — the host never assumes the quantum
            # ran to completion.
            ph = np.zeros((self.serve.slots,), np.int32)
            for s, lane in self._lanes.items():
                if lane.phase == "decode":
                    ph[s] = len(lane.pages)
            if self._pending:
                head = self._pending[0]
                need = -(-min(len(head.ids) + head.max_new_tokens,
                              self.serve.width) // self.serve.page_size)
            else:
                need = 1 << 30
            with self.spans.span("decode"):
                (self.buf, self.cache, self.cursors, self.active, ticks,
                 _) = serve_decode.decode_loop_window(
                    self.params, self.cfg, self.buf, self.cache,
                    self.cursors, self.active, self.limits, self.keys,
                    self._place(ph, self._slot_spec),
                    self._place(np.asarray(self.serve.decode_quantum,
                                           np.int32), P()),
                    self._place(np.asarray(need, np.int32), P()),
                    self.eos_id, float(self.serve.temperature),
                    self._top_k, self.mesh,
                )
            self._pending_ticks = ticks
            if tr is not None:
                # steps is filled at sync, once the device count lands
                self._pending_quantum = dict(
                    t0=t0, t1=tr.now(), steps=0,
                    lanes=[trace_id(l.req)
                           for s, l in sorted(self._lanes.items())
                           if l.phase == "decode"],
                )
            return
        with self.spans.span("decode"):
            self.buf, self.cache, self.cursors, self.active = serve_decode.decode_step(
                self.params, self.cfg, self.buf, self.cache, self.cursors,
                self.active, self.limits, self.keys, self.eos_id,
                float(self.serve.temperature), self._top_k, self.mesh,
                steps=self.serve.decode_quantum,
            )
        if tr is not None:
            # dispatch half of the quantum event; `sync()` adds the
            # wall-to-sync half and emits (one ring record per quantum,
            # not per lane — the ring stays O(quanta))
            self._pending_quantum = dict(
                t0=t0, t1=tr.now(), steps=self.serve.decode_quantum,
                lanes=[trace_id(l.req) for s, l in sorted(self._lanes.items())
                       if l.phase == "decode"],
            )
        self.steps += self.serve.decode_quantum
        self._win["steps"] += self.serve.decode_quantum

    # ---- speculative decoding (round 17, tpukit/serve/spec.py) ----------

    def _spec_step(self) -> None:
        """One draft-and-verify quantum: propose up to `spec_k` tokens per
        slot ("draft" span — a host n-gram lookup or the draft model's
        jitted loop), then score all spec_k+1 positions in ONE batched
        target forward and accept a per-slot prefix ("verify" span).
        Counts as ONE step; a verify can append up to spec_k+1 tokens per
        slot, which is the whole speculation win."""
        from tpukit.serve import spec as spec_lib

        k, n = self.serve.spec_k, self.serve.slots
        # lanes live at dispatch (last sync's view): proposal targets and
        # the telemetry denominator
        live = np.zeros((n,), bool)
        for s, lane in self._lanes.items():
            if lane.phase == "decode":
                live[s] = True
        tr = self.tracer
        t0 = tr.now() if tr is not None else 0.0
        if self.serve.draft == "model":
            with self.spans.span("draft"):
                draft_toks, draft_q, self.draft_cache = spec_lib.draft_propose(
                    self.draft_params, self.draft_cfg, self.buf,
                    self.draft_cache, self.cursors, self.keys,
                    k=k, temperature=float(self.serve.temperature),
                    top_k=self._top_k,
                )
                dlen = np.where(live, k, 0).astype(np.int32)
                draft_len = self._place(
                    np.full((n,), k, np.int32), self._slot_spec
                )
            with self.spans.span("verify"):
                (self.buf, self.cache, self.cursors, self.active, acc,
                 napp) = spec_lib.verify_step(
                    self.params, self.cfg, self.buf, self.cache,
                    self.cursors, self.active, self.limits, self.keys,
                    draft_toks, draft_q, draft_len, self.eos_id,
                    float(self.serve.temperature), self._top_k, k=k,
                    mesh=self.mesh,
                )
        else:
            # self-speculation: the n-gram proposal is FUSED into the
            # verify program (spec.spec_ngram_step) — one dispatch and
            # one sync per quantum, the vanilla step's host rhythm; a
            # host-side proposer would pay buf D2H + draft H2D + a
            # second dispatch every quantum
            with self.spans.span("verify"):
                (self.buf, self.cache, self.cursors, self.active, acc,
                 napp, dlen) = spec_lib.spec_ngram_step(
                    self.params, self.cfg, self.buf, self.cache,
                    self.cursors, self.active, self.limits, self.keys,
                    self.eos_id, float(self.serve.temperature),
                    self._top_k, k=k, max_ngram=self.serve.ngram_max,
                    mesh=self.mesh,
                )
        self._pending_spec = (live, dlen, acc, napp)
        if tr is not None:
            self._pending_quantum = dict(
                t0=t0, t1=tr.now(), steps=1,
                lanes=[trace_id(l.req) for s, l in sorted(self._lanes.items())
                       if l.phase == "decode"],
            )
        self.steps += 1
        self._win["steps"] += 1

    def _drain_spec(self) -> None:
        """Fold the last verify's device counters into the spec telemetry
        (called from the sync fetch — the accepted/appended arrays ride
        the same D2H boundary as the cursors)."""
        if self._pending_spec is None:
            return
        live, dlen, acc, napp = self._pending_spec
        self._pending_spec = None
        acc = np.asarray(jax.device_get(acc))
        napp = np.asarray(jax.device_get(napp))
        for s in np.flatnonzero(live):
            self.spec_proposed += int(dlen[s])
            self.spec_accepted += int(min(acc[s], dlen[s]))
            self.spec_hist[int(napp[s])] += 1

    def _sync_evict(self, now: float) -> None:
        """The per-step host sync: fetch cursors + active flags, retire
        lanes that finished, and account generated tokens. One small D2H
        per step — the price of host-side EOS detection."""
        tr = self.tracer
        s0 = tr.now() if tr is not None else 0.0
        with self.spans.span("sync"):
            if self._pending_spec is not None:
                # coalesce the spec counters into the same D2H round trip
                # (dlen is a device array on the fused ngram path, host
                # numpy on the model path — device_get passes the latter
                # through untouched)
                live, dlen, acc, napp = self._pending_spec
                cur, act, dlen, acc, napp = map(np.asarray, jax.device_get(
                    (self.cursors, self.active, dlen, acc, napp)))
                self._pending_spec = (live, dlen, acc, napp)
            elif self._pending_ticks is not None:
                # fused window (round 21): the actual tick count rides
                # the same D2H round-trip as the cursors — the loop may
                # have exited early, so steps are accounted HERE, from
                # the device's answer, never assumed from the quantum
                cur, act, ticks = map(np.asarray, jax.device_get(
                    (self.cursors, self.active, self._pending_ticks)))
                self._pending_ticks = None
                ran = int(ticks)
                self.steps += ran
                self._win["steps"] += ran
                if self._pending_quantum is not None:
                    self._pending_quantum["steps"] = ran
            else:
                cur, act = map(np.asarray,
                               jax.device_get((self.cursors, self.active)))
            self._drain_spec()
        if tr is not None and self._pending_quantum is not None:
            # complete the dispatch+sync pair started in _step/_spec_step:
            # [t0,t1] is the async-dispatch wall, [s0,s1] the wall-to-sync
            # (device) wall — the per-quantum attribution ROADMAP #3 wants
            q = self._pending_quantum
            self._pending_quantum = None
            tr.emit("quantum", -1, t0=q["t0"], t1=q["t1"], s0=s0,
                    s1=tr.now(), steps=q["steps"], lanes=q["lanes"],
                    replica=self.replica)
        # prefilling paged lanes are act=False by design, not finished;
        # stuck_request-pinned lanes (chaos, round 24) are REFUSED
        # retirement — they hold their slot until deadline eviction
        finished = [
            s for s, lane in self._lanes.items()
            if lane.phase == "decode" and not act[s]
            and lane.req.rid not in self.stuck_rids
        ]
        gen_live = sum(
            int(cur[s]) - lane.prompt_len
            for s, lane in self._lanes.items()
            if lane.phase == "decode" and s not in finished
        )
        if finished:
            host_buf = np.asarray(jax.device_get(self.buf))
            fin_t = tr.now() if tr is not None else 0.0
            for s in finished:
                lane = self._lanes.pop(s)
                length = int(cur[s])
                generated = length - lane.prompt_len
                ids = host_buf[s, :length].copy()
                if self.serve.paged:
                    # a prefix-hit admission SKIPS its shared chunks, so the
                    # buffer row never received those prompt tokens (their
                    # K/V lives in the shared pages; decode never reads buf
                    # below prompt_len-1, which is always in a dispatched
                    # chunk) — the completion's prompt comes from the
                    # request itself
                    ids[: lane.prompt_len] = lane.req.ids
                reason = (
                    "length"
                    if length >= min(lane.prompt_len + lane.req.max_new_tokens,
                                     self.serve.width)
                    else "eos"
                )
                self.evicted[reason] += 1
                self.completions.append(Completion(
                    rid=lane.req.rid, ids=ids,
                    prompt_len=lane.prompt_len, generated=generated,
                    reason=reason, arrival_s=lane.req.arrival_s,
                    admit_s=lane.admit_s, done_s=now,
                    pages=len(lane.pages), prefix_pages=lane.shared,
                    active_s=lane.active_s or lane.admit_s,
                ))
                if tr is not None:
                    # finish is stamped POST-sync (fin_t > done_s=now,
                    # which was captured pre-sync): the last quantum's
                    # sync wall belongs inside the tree's lifetime, so
                    # the phase walls can sum to the tree's e2e
                    tr.emit("finish", trace_id(lane.req), rid=lane.req.rid,
                            t=fin_t, reason=reason, generated=generated,
                            replica=self.replica)
                if self.serve.paged:
                    # drop this lane's references: private pages free (or
                    # retire into the prefix LRU if registered), shared
                    # pages survive for their other readers — and zero the
                    # block-table row so any stale in-flight write lands
                    # in the null page, never in a re-issued one
                    self.allocator.release(lane.pages)
                    self._bt[s] = 0
                    self._bt_dirty = True
                self._free.append(s)
        self._gen_total = sum(c.generated for c in self.completions) + gen_live

    def _evict_deadlines(self, now: float) -> None:
        """Retire decode-resident lanes whose end-to-end deadline_ms has
        expired (round 24): the partial output becomes a Completion with
        reason=\"deadline\" plus a `kind=\"deadline_miss\"` JSONL record,
        and the paged engine parks the lane's pages cheaply (release →
        registered lead pages retire into the prefix LRU, private pages
        free, block-table row zeroed — the same write-safety spelling as
        natural retirement). Runs AFTER _sync_evict, so the quantum is
        already synced and the extra cursor/buffer fetch happens only on
        the rare eviction path. Prefill-phase lanes wait for their decode
        transition (one chunk of grace) so an in-flight chunk never
        targets released pages."""
        over = [
            (s, lane) for s, lane in self._lanes.items()
            if lane.phase == "decode" and lane.req.deadline_ms > 0
            and (now - lane.req.arrival_s) * 1e3 > lane.req.deadline_ms
        ]
        if not over:
            return
        cur, host_buf = map(
            np.asarray, jax.device_get((self.cursors, self.buf))
        )
        tr = self.tracer
        fin_t = tr.now() if tr is not None else 0.0
        for s, lane in over:
            self._lanes.pop(s)
            length = int(cur[s])
            generated = max(length - lane.prompt_len, 0)
            ids = host_buf[s, :length].copy()
            if self.serve.paged:
                ids[: lane.prompt_len] = lane.req.ids
            self.evicted["deadline"] += 1
            over_ms = (now - lane.req.arrival_s) * 1e3 - lane.req.deadline_ms
            self.completions.append(Completion(
                rid=lane.req.rid, ids=ids,
                prompt_len=lane.prompt_len, generated=generated,
                reason="deadline", arrival_s=lane.req.arrival_s,
                admit_s=lane.admit_s, done_s=now,
                pages=len(lane.pages), prefix_pages=lane.shared,
                active_s=lane.active_s or lane.admit_s,
            ))
            if self.logger is not None:
                rec = dict(
                    kind="deadline_miss", rid=lane.req.rid,
                    deadline_ms=lane.req.deadline_ms,
                    over_ms=round(over_ms, 3), generated=generated,
                )
                if self.replica is not None:
                    rec["replica"] = self.replica
                self.logger.log(**rec)
            if self.metrics is not None:
                self.metrics.inc("serve_deadline_miss")
            if tr is not None:
                tr.emit("finish", trace_id(lane.req), rid=lane.req.rid,
                        t=fin_t, reason="deadline", generated=generated,
                        replica=self.replica)
            if self.serve.paged:
                self.allocator.release(lane.pages)
                self._bt[s] = 0
                self._bt_dirty = True
            self._free.append(s)
        # _gen_total is untouched: the evicted tokens were already counted
        # through the last sync's gen_live term, and the next _sync_evict
        # recomputes from completions + live lanes

    # ---- telemetry -------------------------------------------------------

    def _emit_window(self) -> None:
        b = self.spans.window()
        comps = self.completions[self._win["comps0"]:]
        new_tokens = self._gen_total - self._win["gen0"]
        steps = self._win["steps"]
        # occupancy = slot-step utilization: the fraction of slot x decode-
        # tick capacity this window that actually yielded a token (frozen
        # finished lanes and drained tails read as idle — honest). Under
        # speculation a "step" is one verify dispatch with a per-slot
        # emission capacity of spec_k + 1, so the denominator widens.
        cap = (self.serve.spec_k + 1) if self.serve.draft else 1
        rec = dict(
            kind="serve", window=self._window_idx, steps=steps,
            new_tokens=new_tokens,
            tokens_per_sec=(new_tokens / b["total_s"]) if b["total_s"] else None,
            occupancy=(new_tokens / (self.serve.slots * steps * cap))
            if steps else 0.0,
            admitted=self.admitted - self._win["admit0"],
            completed=len(comps), queue_depth=len(self._pending),
            slots=self.serve.slots, window_s=b["total_s"],
            seconds=b["seconds"], fractions=b["fractions"],
            p50_e2e_s=_pct([c.e2e_s for c in comps], 50),
            p99_e2e_s=_pct([c.e2e_s for c in comps], 99),
            p50_token_s=_pct([c.per_token_s for c in comps], 50),
            p99_token_s=_pct([c.per_token_s for c in comps], 99),
            # explicit residual (round 20, the fit() goodput discipline):
            # the window's named spans + other_s sum to window_s exactly
            # — drift can't silently vanish
            other_s=b["seconds"].get("other", 0.0),
            # per-window dispatch-vs-device attribution (ROADMAP #3):
            # decode/draft/verify spans ARE the async dispatch calls;
            # the device's compute wall surfaces as the sync span
            dispatch_overhead_s=(b["seconds"].get("decode", 0.0)
                                 + b["seconds"].get("draft", 0.0)
                                 + b["seconds"].get("verify", 0.0)),
            device_s=b["seconds"].get("sync", 0.0),
        )
        if self.serve.paged:
            # the paged health triple (round 15): pool pressure, how much
            # admission work prefix reuse is deleting, and the per-request
            # footprint the ring design couldn't see
            hits = self.allocator.stats.prefix_hits - self._win["hits0"]
            rec["page_occupancy"] = self.allocator.occupancy
            rec["prefix_hit_rate"] = (
                hits / rec["admitted"] if rec["admitted"] else None
            )
            rec["pages_per_request"] = (
                float(np.mean([c.pages for c in comps])) if comps else None
            )
        if self.serve.draft:
            # the spec health triple (round 17): how much of the draft the
            # target accepted, the per-verify emission shape, and the
            # draft/verify wall split (rides the spans already in rec)
            prop = self.spec_proposed - self._win["prop0"]
            acc = self.spec_accepted - self._win["acc0"]
            rec["spec"] = dict(
                draft=self.serve.draft, k=self.serve.spec_k,
                proposed=prop, accepted=acc,
                accept_rate=(acc / prop) if prop else None,
                accepted_hist=[
                    h - h0 for h, h0 in zip(self.spec_hist, self._win["hist0"])
                ],
            )
        if self.replica is not None:
            rec["replica"] = self.replica
        if self.logger is not None:
            self.logger.log(**rec)
        if self.recorder is not None:
            self.recorder.record(
                "serve", window=self._window_idx, steps=steps,
                new_tokens=new_tokens, occupancy=rec["occupancy"],
                completed=len(comps),
            )
        if self.metrics is not None:
            self._metrics_window(comps, rec)
        self._window_idx += 1
        self._win = dict(
            steps=0, gen0=self._gen_total, admit0=self.admitted,
            comps0=len(self.completions),
            hits0=self.allocator.stats.prefix_hits if self.serve.paged else 0,
            prop0=self.spec_proposed, acc0=self.spec_accepted,
            hist0=list(self.spec_hist),
        )

    def _metrics_window(self, comps, rec: dict) -> None:
        """Fold one window into the metric registry and account the
        declared SLOs — pure derivation from already-produced data
        (completions, trace trees, quantum events); the step primitives
        never see this code."""
        m = self.metrics
        rep = self.replica
        # per-completion latency histograms + deterministic counters.
        # ttft = arrival -> decode-ready (queue wait + prefill +
        # handoff), the trace-tree partition read off the Completion
        # timestamps the engine already stamps.
        for c in comps:
            m.observe("serve_e2e_s", c.e2e_s, replica=rep)
            m.observe("serve_ttft_s", max(c.active_s - c.arrival_s, 0.0),
                      replica=rep)
            m.observe("serve_queue_wait_s", max(c.admit_s - c.arrival_s, 0.0),
                      replica=rep)
            m.observe("serve_tpot_s", c.per_token_s, replica=rep)
            m.observe("serve_tokens_per_request", c.generated, replica=rep)
            m.inc("serve_requests", 1, replica=rep, reason=c.reason)
            m.inc("serve_tokens", c.generated, replica=rep)
        # window gauges (point-in-time; replica-labeled so merges keep
        # every replica's latest)
        if rec.get("tokens_per_sec") is not None:
            m.gauge("serve_tokens_per_sec", rec["tokens_per_sec"], replica=rep)
        m.gauge("serve_occupancy", rec["occupancy"], replica=rep)
        m.gauge("serve_queue_depth", rec["queue_depth"], replica=rep)
        if self.serve.paged:
            m.gauge("serve_page_occupancy", rec["page_occupancy"], replica=rep)
        if self.tracer is not None:
            # phase walls from newly-closed span trees (trees are cheap
            # to rebuild at window cadence; the seen-set keeps each
            # request observed exactly once even though the ring is
            # fleet-shared)
            rids = {c.rid for c in self.completions}
            for t in trace_lib.build_trees(self.tracer.snapshot()):
                if (t["trace"] in self._metrics_traces_seen
                        or not t["closed"] or t["rid"] not in rids):
                    continue
                self._metrics_traces_seen.add(t["trace"])
                for ph, wall in t["phases"].items():
                    m.observe("serve_phase_s", wall, replica=rep, phase=ph)
            # per-quantum dispatch-vs-sync walls, watermarked so each
            # quantum lands once (events are time-sorted by snapshot())
            mark = self._metrics_q_mark
            for ev in self.tracer.snapshot():
                if (ev.get("ev") != "quantum"
                        or ev.get("replica") != rep
                        or ev.get("t1", 0.0) <= mark):
                    continue
                self._metrics_q_mark = max(self._metrics_q_mark, ev["t1"])
                m.observe("serve_dispatch_s", ev["t1"] - ev["t0"],
                          replica=rep, phase="dispatch")
                if "s1" in ev:
                    m.observe("serve_sync_s", ev["s1"] - ev["s0"],
                              replica=rep, phase="sync")
        if self.slo_accountant is not None:
            samples = {
                "e2e": [c.e2e_s for c in comps],
                "ttft": [max(c.active_s - c.arrival_s, 0.0) for c in comps],
                "queue_wait": [max(c.admit_s - c.arrival_s, 0.0) for c in comps],
                "tpot": [c.per_token_s for c in comps],
            }
            slo_rec = dict(kind="slo", window=self._window_idx,
                           **self.slo_accountant.evaluate(samples))
            if self.replica is not None:
                slo_rec["replica"] = self.replica
            if self.logger is not None:
                self.logger.log(**slo_rec)
            if self.recorder is not None:
                self.recorder.record(
                    "slo", window=self._window_idx,
                    overall_compliance=slo_rec["overall_compliance"],
                )
        if self.metrics_dir:
            metrics_lib.publish_snapshot(
                self.metrics_dir, self.replica or 0, m,
                time_s=time.time(),
            )

    def summary(self, wall_s: float) -> dict:
        comps = self.completions
        rec = dict(
            kind="serve_summary", requests=len(comps),
            slots=self.serve.slots, buckets=list(self.serve.buckets),
            buckets_used=sorted(self.buckets_used),
            generated_tokens=sum(c.generated for c in comps),
            decode_steps=self.steps, wall_s=wall_s,
            tokens_per_sec=(sum(c.generated for c in comps) / wall_s)
            if wall_s else None,
            mean_occupancy=(
                sum(c.generated for c in comps)
                / (self.serve.slots * self.steps
                   * ((self.serve.spec_k + 1) if self.serve.draft else 1))
            ) if self.steps else 0.0,
            admitted=self.admitted, evicted_eos=self.evicted["eos"],
            evicted_length=self.evicted["length"],
            evicted_deadline=self.evicted["deadline"],
            p50_e2e_s=_pct([c.e2e_s for c in comps], 50),
            p99_e2e_s=_pct([c.e2e_s for c in comps], 99),
            p50_token_s=_pct([c.per_token_s for c in comps], 50),
            p99_token_s=_pct([c.per_token_s for c in comps], 99),
        )
        if self.replica is not None:
            rec["replica"] = self.replica
        ep = self.spans.epoch()
        rec["prefill_s"] = ep["seconds"].get("prefill", 0.0)
        rec["decode_s"] = ep["seconds"].get("decode", 0.0)
        rec["sync_s"] = ep["seconds"].get("sync", 0.0)
        # wall clock outside every span, surfaced instead of silently
        # vanishing (the run loop resets the span epoch at its t0, so a
        # standalone run's named + other walls sum to wall_s)
        named = (rec["prefill_s"] + rec["decode_s"] + rec["sync_s"]
                 + ep["seconds"].get("draft", 0.0)
                 + ep["seconds"].get("verify", 0.0))
        rec["other_s"] = max(wall_s - named, 0.0)
        rec["dispatch_overhead_s"] = (rec["decode_s"]
                                      + ep["seconds"].get("draft", 0.0)
                                      + ep["seconds"].get("verify", 0.0))
        rec["device_s"] = rec["sync_s"]
        rec["max_live_slots"] = self.max_live
        rec["kv_bytes"] = self.kv_bytes
        if self.serve.draft:
            rec["draft_s"] = ep["seconds"].get("draft", 0.0)
            rec["verify_s"] = ep["seconds"].get("verify", 0.0)
            rec["spec"] = dict(
                draft=self.serve.draft, k=self.serve.spec_k,
                proposed=self.spec_proposed, accepted=self.spec_accepted,
                accept_rate=(self.spec_accepted / self.spec_proposed)
                if self.spec_proposed else None,
                accepted_hist=list(self.spec_hist),
            )
        if self.serve.paged:
            st = self.allocator.stats
            hit = [c.admit_latency_s for c in comps if c.prefix_pages > 0]
            cold = [c.admit_latency_s for c in comps if c.prefix_pages == 0]
            rec.update(
                page_size=self.serve.page_size, num_pages=self.num_pages,
                kv_dtype=self.serve.kv_dtype,
                prefix_hits=st.prefix_hits,
                prefix_hit_rate=st.prefix_hits / max(self.admitted, 1),
                prefix_pages_reused=st.prefix_pages_reused,
                reclaimed_pages=st.reclaimed,
                page_occupancy=self.allocator.occupancy,
                pages_per_request=float(np.mean([c.pages for c in comps]))
                if comps else None,
                admit_latency_hit_s=float(np.mean(hit)) if hit else None,
                admit_latency_cold_s=float(np.mean(cold)) if cold else None,
            )
        if self.tracer is not None:
            # per-request phase latency percentiles from THIS engine's
            # completed span trees (the tracer may be fleet-shared, so
            # restrict to our own completions)
            rids = {c.rid for c in comps}
            trees = [t for t in trace_lib.build_trees(self.tracer.snapshot())
                     if t["rid"] in rids]
            rec["phase_p50"], rec["phase_p99"] = trace_lib.phase_stats(trees)
            rec["trace_complete"] = trace_lib.completeness(trees)
            # ring evictions poison every aggregate above — surface them
            # instead of letting a saturated ring read as complete
            # (report.py warns when nonzero)
            rec["trace_dropped"] = self.tracer.dropped_by_replica.get(
                self.replica, 0
            )
        if self.slo_accountant is not None:
            rec["slo_overall_compliance"] = (
                self.slo_accountant.overall_compliance()
            )
        return rec

    # ---- step primitives (the fleet hooks, round 19) ---------------------
    # `run()` below is spelled entirely in terms of these, so a FleetRouter
    # (tpukit/serve/fleet.py) driving N engines round-robin exercises the
    # exact scheduling code the standalone loop does — the token-parity
    # guarantee transfers instead of being re-proven.

    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def live_lanes(self) -> int:
        return len(self._lanes)

    @property
    def decoding_lanes(self) -> int:
        return sum(1 for l in self._lanes.values() if l.phase == "decode")

    @property
    def generated_tokens(self) -> int:
        """Tokens generated so far (completed + live lanes, as of the last
        sync) — the fleet router's aggregation counter."""
        return self._gen_total

    @property
    def free_pages(self) -> int:
        """Pages an admission could obtain (free + reclaimable retained);
        the ring has no page budget, so it reports effectively-infinite —
        the router's least-loaded tiebreak never binds on it."""
        return self.allocator.available_pages if self.serve.paged else (1 << 30)

    def admit(self, reqs: list[Request], now: float) -> list[Request]:
        """Admit as many of `reqs` (in order) as capacity allows; returns
        the un-admitted tail. Ring: up to the free-slot count in ONE
        batched bucket-grouped prefill. Paged: head-of-line page-aware
        admission — stops at the first request the pool cannot cover
        (FIFO, no starvation), exactly the run-loop semantics."""
        if not self.serve.paged:
            take = reqs[: len(self._free)]
            if take:
                self._admit_batch(take, now)
            return list(reqs[len(take):])
        left = list(reqs)
        while left and self._free:
            if not self._admit_paged_one(left[0], now):
                break
            left.pop(0)
        return left

    def poll_prefill(self, now: float) -> None:
        """Advance every prefilling paged lane one chunk (no-op on the
        ring, whose prefill is one-shot at admission)."""
        if self.serve.paged:
            self._dispatch_prefill_chunks(now)

    def dispatch_decode(self) -> bool:
        """Dispatch one decode quantum (or spec draft-and-verify quantum)
        if any lane is decoding; returns whether anything was dispatched.
        The dispatch is async — callers overlap several engines' quanta by
        dispatching all of them before the first `sync`."""
        if not any(l.phase == "decode" for l in self._lanes.values()):
            return False
        if self.serve.draft:
            self._spec_step()
        else:
            self._step()
        return True

    def sync(self, now: float) -> None:
        """The per-quantum host sync: fetch cursors/flags, retire finished
        lanes, evict deadline-expired ones, and emit a `kind="serve"`
        window when one is due."""
        self._sync_evict(now)
        self._evict_deadlines(now)
        if self._win["steps"] >= self.serve.window_steps:
            self._emit_window()

    def finish(self, wall_s: float) -> list[Completion]:
        """Flush the partial window and emit the `kind="serve_summary"`
        record; returns the completions. The run loop's epilogue, exposed
        so the fleet can finalize each replica at fleet shutdown."""
        if self._win["steps"]:
            self._emit_window()
        rec = self.last_summary = self.summary(wall_s)
        if self.logger is not None:
            self.logger.log(**rec)
        if self.recorder is not None:
            self.recorder.record(
                "serve_summary", requests=rec["requests"],
                tokens_per_sec=rec["tokens_per_sec"],
                mean_occupancy=rec["mean_occupancy"],
            )
        if self.tracer is not None and self.replica is None:
            # standalone epilogue: persist the ring + span trees into the
            # JSONL (fleet replicas share the router's tracer — the
            # router flushes ONCE at fleet shutdown, covering killed
            # replicas that never reach finish())
            trace_lib.flush_to_logger(
                self.tracer, self.logger,
                trace_lib.build_trees(self.tracer.snapshot()),
            )
        if self.metrics is not None and self.replica is None:
            # standalone metrics epilogue (a fleet's router owns this,
            # same ownership rule as the tracer flush above): the
            # kind="metrics" summary row plus the snapshot-file merge
            rec_m = dict(kind="metrics", source="serve",
                         **self.metrics.summary())
            if self.logger is not None:
                self.logger.log(**rec_m)
            if self.recorder is not None:
                self.recorder.record(
                    "metrics", source="serve",
                    hists=len(rec_m["hists"]),
                    tokens=self.metrics.sum_counter("serve_tokens"),
                )
            if self.metrics_dir:
                metrics_lib.publish_snapshot(
                    self.metrics_dir, self.replica or 0, self.metrics,
                    time_s=time.time(),
                )
                merged, meta = metrics_lib.merge_snapshot_dir(self.metrics_dir)
                metrics_lib.write_merged(self.metrics_dir, merged, meta=meta)
        return self.completions

    def requeue_live(self) -> list[Request]:
        """The in-flight requests of this replica, reconstructed from the
        Request objects themselves — the completion-carries-prompt
        invariant (round 15) means a lane's original prompt never depends
        on device state, so a chaos-killed replica's work re-queues onto
        survivors losslessly: same prompt, same per-request seed, hence
        (engine parity) the same tokens. Partial output is discarded, so
        each request's tokens are emitted exactly once, by whichever
        replica finishes it. Does not mutate the engine — a killed
        replica is simply dropped."""
        return sorted((l.req for l in self._lanes.values()),
                      key=lambda r: r.rid)

    # ---- disaggregated prefill (round 19, tpukit/serve/fleet.py) ---------

    def release_lane(self, slot: int) -> None:
        """Retire lane `slot` WITHOUT a completion — the prefill worker's
        half of the page handoff: once a finished prefix is copied to a
        decode replica, the worker drops its references (registered lead
        pages retire into the prefix LRU for future hits, private pages
        free) and zeroes the block-table row so any stale in-flight write
        lands in the null page (write-safety invariant 2)."""
        lane = self._lanes.pop(slot)
        if self.serve.paged:
            self.allocator.release(lane.pages)
            self._bt[slot] = 0
            self._bt_dirty = True
        self._free.append(slot)

    def adopt_prefilled(self, req: Request, pages: list[int], shared: int,
                        admit_s: float, now: float, key) -> int:
        """Decode-replica half of the disaggregated handoff: arm a lane
        whose K/V pages were prefilled ELSEWHERE (already copied into this
        engine's pool at `pages` by fleet._copy_pages) — the replica never
        runs a prefill program, so its serve-path compile budget is one
        decode program plus this (dynamic-update-slice-only) arm.

        `pages` must already be allocated/claimed on THIS engine's
        allocator (`shared` = how many lead pages are decode-side registry
        claims); the block-table row, buffer row (the full prompt — the
        first decode tick re-forwards position prompt_len-1) and per-slot
        decode state are armed here. Registers the lead
        `(prompt_len-1)//P` pages so later handoffs of the same prefix
        claim them instead of re-copying (write-safety invariant 1: the
        last prompt position's page stays private)."""
        if not self.serve.paged:
            raise ValueError(
                "adopt_prefilled requires the paged cache (page_size > 0) "
                "— the disaggregated handoff rides page granularity"
            )
        plen = len(req.ids)
        slot = self._free.popleft()
        self._bt[slot] = 0
        self._bt[slot, : len(pages)] = pages
        self._bt_dirty = True
        self._refresh_bt()
        row = np.zeros((self.serve.padded_width,), np.int32)
        row[:plen] = req.ids
        limit = min(plen + req.max_new_tokens, self.serve.width)
        key = np.asarray(key, np.uint32)
        (self.buf, self.cursors, self.active, self.limits,
         self.keys) = serve_decode.adopt_slot(
            self.buf, self.cursors, self.active, self.limits, self.keys,
            self._place(np.asarray(slot, np.int32), P()),
            self._place(row, P()),
            self._place(np.asarray(plen, np.int32), P()),
            self._place(np.asarray(limit, np.int32), P()),
            self._place(key, P()),
        )
        reg = (plen - 1) // self.serve.page_size
        self.allocator.register(req.ids, pages[:reg])
        self._lanes[slot] = _Lane(
            req, admit_s, plen, self.bucket_for(plen), pages=list(pages),
            shared=shared, next_chunk=0, prefill_end=0, phase="decode",
            active_s=now, key=key,
        )
        self.admitted += 1
        self.max_live = max(self.max_live, len(self._lanes))
        if self.tracer is not None:
            self.tracer.emit("adopt", trace_id(req), rid=req.rid, t=now,
                             slot=slot, replica=self.replica)
        if shared:
            self.allocator.stats.prefix_hits += 1
            self.allocator.stats.prefix_pages_reused += shared
        return slot

    # ---- the loop --------------------------------------------------------

    def run(self, requests, max_wall_s: float | None = None) -> list[Completion]:
        """Serve `requests` (admitted no earlier than their `arrival_s`)
        to completion. Admission fills free slots between decode steps —
        an arriving prefill never stalls an active slot's decode — and a
        request whose prompt exceeds every bucket raises at admission.
        Emits a `kind="serve"` window every `window_steps` decode steps
        and a final `kind="serve_summary"`; returns the completions in
        finish order."""
        self._pending = deque(sorted(requests, key=lambda r: (r.arrival_s, r.rid)))
        # reset the span epoch to the RUN start (round 20): the timeline
        # was constructed earlier, and the construction->run gap would
        # otherwise leak into the summary's `other_s` residual
        self.spans.epoch()
        t0 = time.perf_counter()
        if self.tracer is not None:
            self.tracer.set_epoch(t0)
            for r in self._pending:
                self.tracer.emit("enqueue", trace_id(r), rid=r.rid,
                                 t=r.arrival_s, replica=self.replica)
        now = 0.0
        while self._pending or self._lanes:
            now = time.perf_counter() - t0
            if max_wall_s is not None and now > max_wall_s:
                raise TimeoutError(
                    f"serve run exceeded max_wall_s={max_wall_s} with "
                    f"{len(self._pending)} pending / {len(self._lanes)} live"
                )
            # page-aware admission control (paged): a request needs a free
            # lane AND its worst-case page footprint; the head of the queue
            # waits (FIFO, no starvation) when the pool can't cover it
            ready: list[Request] = []
            while (self._pending and len(ready) < len(self._free)
                   and self._pending[0].arrival_s <= now):
                ready.append(self._pending.popleft())
            for req in reversed(self.admit(ready, now)):
                self._pending.appendleft(req)
            self.poll_prefill(time.perf_counter() - t0)
            if not self.dispatch_decode():
                if not self._lanes and self._pending:
                    # nothing decoding and the next arrival is in the future
                    wait = self._pending[0].arrival_s - now
                    if wait > 0:
                        time.sleep(min(wait, 0.05))
                continue
            self.sync(time.perf_counter() - t0)
        return self.finish(time.perf_counter() - t0)


STREAM_PROFILES = ("uniform", "repetitive", "shared_prefix")


def synthetic_request_stream(tokenizer, n: int, *, seed: int = 0,
                             max_new_tokens: int = 16,
                             buckets=(16, 32), qps: float = 0.0,
                             corpus=None, lengths=None,
                             shared_prefix: int = 0,
                             stream_profile: str = "uniform") -> list[Request]:
    """Seeded synthetic request stream: prompts cut from the offline
    fixture corpus at seeded lengths spanning the bucket set, arrivals
    all-at-once (qps=0, an offered-load saturation test) or spaced by a
    seeded exponential process (qps>0). Deterministic per seed — the
    serving bench compares continuous vs serial on the SAME stream.
    `lengths` restricts the drawn prompt lengths to a fixed set (the
    bench uses it so the SERIAL baseline's per-prompt-length compiles
    stay bounded; the engine is bucket-bounded either way).

    `stream_profile` (round 17) names the workload SHAPE so a bench or
    test run is reproducible from one spelling (`--stream_profile` in
    main-serve.py):

      - "uniform" (default): the original per-request corpus cuts.
      - "repetitive": each prompt is a short seeded phrase (2-4 tokens)
        TILED to its target length — the structured/templated traffic
        shape where self-speculation (n-gram drafting, spec.py) wins:
        histories recur by construction, so prompt-lookup proposals land.
      - "shared_prefix": every request shares one system prompt; uses
        `shared_prefix` (defaulting it to half the largest bucket when
        unset) — the paged prefix-reuse shape (round 15).

    `shared_prefix > 0` prepends the SAME `shared_prefix`-token system
    prompt (cut from the corpus head) to every request — the
    millions-of-users-one-system-prompt shape that paged prefix reuse
    (round 15) exists for. Bodies stay per-request; combined prompts are
    truncated to the largest bucket."""
    from tpukit.data import synthetic_stories

    if stream_profile not in STREAM_PROFILES:
        raise ValueError(
            f"stream_profile={stream_profile!r} must be one of "
            f"{STREAM_PROFILES}"
        )
    rng = np.random.RandomState(seed)
    corpus = corpus if corpus is not None else synthetic_stories(max(64, n))
    if stream_profile == "shared_prefix" and shared_prefix <= 0:
        shared_prefix = max(buckets) // 2
    prefix: list[int] = []
    if shared_prefix > 0:
        prefix = list(tokenizer(
            [" ".join(corpus)], truncation=True, max_length=shared_prefix
        )["input_ids"][0])
    out = []
    t = 0.0
    for i in range(n):
        text = corpus[int(rng.randint(len(corpus)))]
        if lengths is not None:
            target = int(lengths[int(rng.randint(len(lengths)))])
        else:
            target = int(rng.randint(4, max(buckets) + 1))
        ids = tokenizer([text], truncation=True, max_length=target)["input_ids"][0]
        if stream_profile == "repetitive":
            phrase = list(ids)[: int(rng.randint(2, 5))]
            reps = -(-target // max(len(phrase), 1))
            ids = (phrase * reps)[:target]
        ids = (prefix + list(ids))[: max(buckets)]
        if qps > 0:
            t += float(rng.exponential(1.0 / qps))
        out.append(Request(
            rid=i, ids=tuple(int(x) for x in ids),
            max_new_tokens=max_new_tokens, seed=seed + i, arrival_s=t,
        ))
    return out
