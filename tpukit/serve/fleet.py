"""Fleet serving: a request router over N engine replicas (round 19).

ROADMAP #1's last open stage. The round-14 engine is deliberately a
single-host scheduler over ONE device grid; this module is the layer that
takes it to "millions of users" shape: N data-parallel `ServeEngine`
replicas, each constructed on a DISJOINT device subset of the host's mesh
(the round-15 grid picker already takes device subsets, so N replicas x
model-parallel grids coexist in one process), behind one router that owns
the shared request stream. Three capabilities ride on that:

  - **Least-loaded admission**: the router holds the global FIFO queue and
    assigns each arrived request to the replica with the most free slots
    (ties broken by free pages, then lowest replica id). Per-request seeds
    travel WITH the request, and every replica's scheduling is the proven
    engine (each completion is token-for-token the serial cached decode of
    its own prompt + seed, whatever the admit/evict interleaving) — so the
    fleet's output is token-identical to a single engine consuming the
    same stream, the parity bar every serve round has held
    (tests/test_fleet.py).
  - **Disaggregated prefill** (`FleetConfig.disagg_prefill`, paged only):
    a dedicated prefill worker runs chunked prefill into its OWN paged
    pool; a finished prefix hands off to a decode replica as pages — the
    decode side first CLAIMS any already-registered prefix pages from its
    own registry (refcounted read-only, the round-15 machinery), then the
    remaining written pages are copied device-to-device
    (`paged.extract_pages` -> one `jax.device_put` at the destination
    layout -> `paged.insert_pages`) into freshly allocated exclusive
    pages, and `ServeEngine.adopt_prefilled` arms the lane. Decode
    replicas never execute a prefill program: their serve-path compile
    budget shrinks to ONE decode program plus the trivial
    `decode.adopt_slot` arm.
  - **Occupancy-driven autoscale + replica failure**: between fleet
    windows the router compares mean slot occupancy against the
    up/down thresholds and grows (build a fresh grid on a free device
    subset — the reshard `resize@N:M` pattern: rebuild, don't mutate) or
    shrinks (drain: no new admissions, in-flight requests finish, then
    the replica retires and its devices free). A chaos-killed replica
    (`replica_kill@R[:idx]`, tpukit/chaos.py — fleet-scoped grammar) is
    dropped mid-flight: its in-flight requests re-queue onto survivors
    with the prompt reconstructed from the Request itself
    (completion-carries-prompt, round 15) and the same per-request seed,
    so each request's tokens are emitted EXACTLY once and are identical
    to the un-killed run's.

Comm story: the router is pure host-side scheduling — it adds ZERO
collectives. Each replica's decode program is the round-14 program on a
subset mesh, audited unchanged against `decode_step_comm`'s closed form
(`analysis.plan.fleet_decode_comm_plan`, the hlolint `fleet_decode`
world). Decode quanta for all replicas are DISPATCHED before any is
synced, so disjoint-subset replicas overlap on the device side; the
router's own work between dispatches is queue arithmetic.

Telemetry: replicas emit their usual `kind="serve"` windows tagged
`replica=<id>`; the router adds `kind="fleet"` windows (aggregate
tokens/s, per-replica occupancy, queue depth), `kind="fleet_event"`
(scale/kill/requeue) and one `kind="fleet_summary"` — rendered by
`tools/report.py` "== fleet ==" with the `--min_fleet_tps` CI gate.
With a shared `tracer` (round 20, tpukit/obs/trace.py) the router also
emits route/handoff/requeue span events — merged with the replicas'
admit/prefill/quantum/finish events into per-request span trees whose
fleet-wide per-phase p50/p99 and completeness land on the summary, and
which flush to `kind="trace_event"`/`kind="trace"` JSONL rows for the
`--min_trace_complete` gate and `tools/traceview.py`.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from pathlib import Path

import numpy as np

from tpukit import chaos as chaos_lib
from tpukit import recovery as recovery_lib
from tpukit import retry as retry_lib
from tpukit.obs import metrics as metrics_lib
from tpukit.obs import trace as trace_lib
from tpukit.serve import ledger as ledger_lib
from tpukit.serve import paged as paged_lib
from tpukit.serve.engine import (
    Completion,
    Request,
    ServeConfig,
    ServeEngine,
    trace_id,
)


def pick_serve_grid(n_devices: int, heads: int, slots: int,
                    paged: bool = False) -> dict:
    """(data x model) serving grid: the largest model degree <= 4 dividing
    both the device count and the head count (the KV ring shards heads
    over `model`; main-tp.py's rule), remaining devices data-parallel —
    shrunk to the largest divisor of the slot count, since slots shard
    over `data`. Paged serving (round 15) requires a MODEL-ONLY grid —
    the page pool is replicated across `data`, so a data axis > 1 would
    make the pool write-back an unauditable cross-shard scatter
    (serve.decode.decode_step_comm) — and therefore drops the <= 4 cap:
    `model` grows to the LARGEST head-dividing degree so devices the
    ring would have used as `data` aren't silently stranded.

    Moved here from main-serve.py in round 19: the fleet builds one grid
    PER REPLICA over that replica's device subset, so the picker is
    shared infrastructure, not recipe code."""
    if paged:
        # data is pinned to 1, so n_devices divisibility buys nothing —
        # create_mesh takes a device subset when model < n_devices; only
        # the head count constrains the degree
        for model in range(min(n_devices, heads), 0, -1):
            if heads % model == 0:
                if model < n_devices:
                    print(f"paged serving uses a model-only grid: "
                          f"model={model} of {n_devices} devices "
                          f"(model degree is capped by heads={heads})")
                return {"data": 1, "model": model}
    for model in (4, 2, 1):
        if n_devices % model == 0 and heads % model == 0:
            data = n_devices // model
            while data > 1 and slots % data:
                data -= 1
            return {"data": data, "model": model}
    return {"data": 1, "model": 1}


def place_replica_params(host_params, mesh):
    """Place ONE host copy of the params at a replica's shardings — the
    shared-cold-start half the router leans on: the checkpoint is read
    once (`checkpoint.restore_params(..., sharding_tree=None)` keeps the
    leaves on host), and every replica placement is a device_put of the
    SAME host arrays, no further I/O. Meshless replicas (mesh=None) get
    plainly-committed arrays; meshed replicas get the TensorParallel
    training shardings over their own subset mesh (the round-14 serving
    placement)."""
    import jax
    import jax.numpy as jnp

    if mesh is None:
        return jax.tree.map(lambda x: jnp.asarray(np.asarray(x)), host_params)
    from tpukit.shardings import TensorParallel

    strat = TensorParallel(mesh)
    shapes = jax.eval_shape(lambda: jax.tree.map(np.asarray, host_params))
    sharding = strat.state_sharding(shapes)
    return jax.tree.map(
        lambda x, s: jax.device_put(np.asarray(x), s), host_params, sharding
    )


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Router shape. Replicas share ONE `ServeConfig` (the per-replica
    engine shape); the fleet adds the topology — how many engines, over
    which device subsets — and the control loops on top."""

    # Initial replica count. Each replica is a full ServeEngine with its
    # own KV cache/pool on its own device subset.
    replicas: int = 2
    # Devices per replica subset. 0 = meshless replicas (every engine on
    # the default device — the test/CPU shape, where the router logic is
    # identical and only the grids are trivial). > 0 carves
    # jax.devices() into disjoint subsets of this size; each replica's
    # grid comes from pick_serve_grid over its subset.
    devices_per_replica: int = 0
    # Autoscale bounds. max_replicas 0 = the initial count (no scale-up
    # headroom); with devices_per_replica > 0 the device list must cover
    # max_replicas subsets (validated at construction).
    min_replicas: int = 1
    max_replicas: int = 0
    # Occupancy thresholds (fraction of live-replica slot capacity holding
    # a decoding lane, mean over a fleet window). 0 disables that
    # direction. Scale-up builds a fresh grid on a free subset; scale-down
    # DRAINS the highest-id live replica (no new admissions, in-flight
    # requests finish) then retires it — never evicts work.
    scale_up_occupancy: float = 0.0
    scale_down_occupancy: float = 0.0
    # Fleet window cadence, in dispatch rounds (a round = one decode
    # quantum dispatched per live replica). Windows drive both the
    # kind="fleet" record and the autoscale check.
    window_steps: int = 16
    # Disaggregated prefill (paged only): one dedicated prefill worker
    # owns admission + chunked prefill; decode replicas only decode.
    disagg_prefill: bool = False
    prefill_slots: int = 0  # 0 = the ServeConfig's slot count
    prefill_pages: int = 0  # 0 = the ServeConfig's pool default
    # Deterministic replica failure: the fleet-scoped chaos grammar
    # (chaos.validate_fleet_spec — ONE parse/validation path with
    # --chaos_spec since round 24): replica_kill@R[:idx],
    # replica_sigkill@R[:idx], slow_replica@R:ms, stuck_request@RID,
    # ledger_io_fail@K[:c].
    kill_spec: str = ""
    # Crash-consistency plane (round 24, serve/ledger.py). fleet_dir
    # roots the durable request ledger (write-ahead leases, exactly-once
    # completion records, replay on restart) and the replica heartbeat
    # files; empty keeps the round-19 in-memory lifecycle.
    fleet_dir: str = ""
    # Liveness: a replica whose heartbeat is older than this (seconds)
    # is declared dead — leases revoked, in-flight requests requeued
    # onto survivors. 0 disables the check; > 0 requires fleet_dir (the
    # liveness plane IS the heartbeat files).
    replica_timeout: float = 0.0
    # Requeue budget per request: a request survives at most this many
    # REASSIGNMENTS after its first (jittered-backoff-spaced, the
    # retry.backoff_delay spelling); exhaustion lands it as a named
    # `request_failed` event, never an infinite kill->requeue loop.
    request_retries: int = 3
    # Backpressure: when more than this many ARRIVED requests are
    # queued, the lowest-priority (then latest) admissions shed with a
    # named `request_rejected` event instead of queueing unboundedly.
    # 0 = unbounded (the round-19 behavior).
    max_queue_depth: int = 0

    def __post_init__(self):
        if self.replicas < 1:
            raise ValueError(f"replicas={self.replicas} must be >= 1")
        if self.min_replicas < 1 or self.min_replicas > self.replicas:
            raise ValueError(
                f"min_replicas={self.min_replicas} must be in "
                f"[1, replicas={self.replicas}]"
            )
        if self.max_replicas and self.max_replicas < self.replicas:
            raise ValueError(
                f"max_replicas={self.max_replicas} must be 0 (= replicas) "
                f"or >= replicas={self.replicas}"
            )
        if self.devices_per_replica < 0:
            raise ValueError(
                f"devices_per_replica={self.devices_per_replica} must be >= 0"
            )
        for name in ("scale_up_occupancy", "scale_down_occupancy"):
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"{name}={v} must be in [0, 1]")
        if (self.scale_up_occupancy and self.scale_down_occupancy
                and self.scale_down_occupancy >= self.scale_up_occupancy):
            raise ValueError(
                f"scale_down_occupancy={self.scale_down_occupancy} must be "
                f"< scale_up_occupancy={self.scale_up_occupancy} — equal or "
                f"inverted thresholds would oscillate every window"
            )
        if self.window_steps < 1:
            raise ValueError(f"window_steps={self.window_steps} must be >= 1")
        for name in ("prefill_slots", "prefill_pages"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name}={getattr(self, name)} must be >= 0")
        if (self.prefill_slots or self.prefill_pages) and not self.disagg_prefill:
            raise ValueError(
                "prefill_slots/prefill_pages configure the dedicated "
                "prefill worker — set disagg_prefill=True to run one"
            )
        # the kill plan must parse at construction (chaos's fail-at-startup
        # contract) — ONE grammar/validation path with --chaos_spec
        # (round 24 retired the bespoke check this used to carry)
        chaos_lib.validate_fleet_spec(self.kill_spec)
        if self.replica_timeout < 0:
            raise ValueError(
                f"replica_timeout={self.replica_timeout} must be >= 0"
            )
        if self.replica_timeout > 0 and not self.fleet_dir:
            raise ValueError(
                "replica_timeout needs fleet_dir: liveness is declared "
                "from the heartbeat FILES replicas publish there"
            )
        if self.request_retries < 0:
            raise ValueError(
                f"request_retries={self.request_retries} must be >= 0"
            )
        if self.max_queue_depth < 0:
            raise ValueError(
                f"max_queue_depth={self.max_queue_depth} must be >= 0"
            )

    @property
    def max_count(self) -> int:
        return self.max_replicas or self.replicas


class FleetRouter:
    """The fleet: N `ServeEngine` replicas behind one request queue.

    `params_host` is ONE host-side copy of the model params (numpy leaves
    or device arrays — they are np.asarray'd once); the router places it
    per replica (`place_replica_params`), so a checkpoint is read exactly
    once however many replicas serve it. `serve` is the per-replica
    engine shape; `fleet` the topology/control config. `logger`/
    `recorder` flow into every replica (windows tagged `replica=<id>`)
    and carry the router's own fleet records."""

    def __init__(self, params_host, cfg, serve: ServeConfig,
                 fleet: FleetConfig, eos_id: int, *, devices=None,
                 logger=None, recorder=None, tracer=None, metrics=None,
                 slo=None, metrics_dir=None):
        import jax

        if serve.draft and fleet.disagg_prefill:
            # unreachable via ServeConfig (draft requires the ring, disagg
            # the pages) — kept as a named guard for direct construction
            raise ValueError("disagg_prefill and speculative decoding are "
                             "mutually exclusive (ServeConfig enforces "
                             "draft => ring cache)")
        if fleet.disagg_prefill and not serve.paged:
            raise ValueError(
                "disagg_prefill requires the paged cache (page_size > 0): "
                "the prefill->decode handoff rides page granularity — "
                "refcounted read-only pages are the transferable unit"
            )
        if fleet.devices_per_replica and cfg.num_experts > 0:
            raise ValueError(
                "fleet MoE serving uses meshless replicas this round "
                "(devices_per_replica=0): the Megatron grid rules don't "
                "cover expert banks (main-serve.py serves MoE replicated)"
            )
        self.cfg = cfg
        self.serve = serve
        self.fleet = fleet
        self.eos_id = int(eos_id)
        self.logger = logger
        self.recorder = recorder
        # ONE TraceRecorder shared by the router, every replica and the
        # prefill worker (round 20): fleet span trees need a single
        # clock and ring set that survives replica kills, so the router
        # owns it and flushes it once at fleet shutdown.
        self.tracer = tracer
        # ONE MetricRegistry shared the same way (round 22): every
        # replica engine observes into it replica-labeled, the router
        # accounts the fleet-level SLOs and owns the snapshot-file
        # publish/merge — per-replica files split out of the shared
        # registry by label, process-0-merges them back by bucket sum
        # (the proof harness for ROADMAP #1's cross-process metrics).
        self.metrics = metrics
        self.slo_accountant = (
            metrics_lib.SloAccountant(slo)
            if (metrics is not None and slo) else None
        )
        self.metrics_dir = metrics_dir
        self._slo_seen_rids: set = set()
        self._metrics_replicas: set = set()  # every replica id ever built
        self._params_host = params_host
        self.placements = 0
        self._placed: dict[int, object] = {}  # subset idx -> placed params

        dpr = fleet.devices_per_replica
        devices = list(devices if devices is not None else jax.devices())
        self._subsets: list = []
        if dpr:
            need = fleet.max_count * dpr
            if need > len(devices):
                raise ValueError(
                    f"max_replicas={fleet.max_count} x devices_per_replica="
                    f"{dpr} needs {need} devices, have {len(devices)}"
                )
            self._subsets = [
                devices[i * dpr: (i + 1) * dpr]
                for i in range(fleet.max_count)
            ]
            # a spare subset beyond the replica budget hosts the prefill
            # worker; otherwise the worker runs meshless
            self._worker_devices = (
                devices[need: need + dpr] if len(devices) >= need + dpr
                else None
            )
        else:
            self._subsets = [None] * fleet.max_count
            self._worker_devices = None

        # counters the fleet summary reports (initialized before the
        # replicas exist — _build_replica updates replicas_peak)
        self.requeued = 0
        self.kills = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.handoffs = 0
        self.replicas_peak = 0
        # robustness plane (round 24)
        self.rejected = 0
        self.request_failures = 0
        self.replicas_dead = 0
        self.leases_revoked = 0
        self._attempts: dict[int, int] = {}    # rid -> assignments so far
        self._not_before: dict[int, float] = {}  # rid -> backoff gate
        self._stalled_until: dict[int, float] = {}  # replica -> wall time
        self._ledger_marks: dict[int, int] = {}  # id(engine) -> published
        self._replayed = 0
        self._last_beat_t = 0.0                # heartbeat publish throttle
        self._last_live_t = 0.0                # liveness check throttle

        # the serving chaos plan (ONE grammar with --chaos_spec): kills/
        # sigkills/stalls are round-indexed, ledger I/O faults occurrence-
        # indexed through the module hook the router installs during run()
        self._chaos = chaos_lib.ServingChaos(fleet.kill_spec)

        # durable request lifecycle + liveness plane (round 24)
        self.ledger = (
            ledger_lib.RequestLedger(fleet.fleet_dir)
            if fleet.fleet_dir else None
        )
        self._hb_dir = (
            Path(fleet.fleet_dir) / "heartbeats" if fleet.fleet_dir else None
        )
        self._done: list[Completion] = []      # retired/killed replicas'
        self._gen_removed = 0                  # their generated tokens
        self._replica_stats: dict = {}         # id -> final per-replica row
        self._window_idx = 0
        self._win = dict(rounds=0, occ=0.0, tok0=0, t0=0.0, req0=0)

        self._replicas: dict[int, ServeEngine] = {}
        self._draining: set[int] = set()
        for idx in range(fleet.replicas):
            self._build_replica(idx, log=False)

        self.prefill: ServeEngine | None = None
        if fleet.disagg_prefill:
            wcfg = dataclasses.replace(
                serve,
                slots=fleet.prefill_slots or serve.slots,
                num_pages=fleet.prefill_pages or serve.num_pages,
            )
            wmesh = self._make_mesh(self._worker_devices)
            self.prefill = ServeEngine(
                self._place_for(wmesh, subset_idx=-1), cfg, wcfg,
                eos_id=self.eos_id, mesh=wmesh, logger=None, recorder=None,
                replica="prefill", tracer=self.tracer,
            )

        # kill plan (round 19; via ServingChaos since round 24): dispatch
        # round -> target ids (None = highest live). The in-process
        # router fires replica_sigkill as replica_kill — there is no
        # process to kill — and says so in the event; real SIGKILL lives
        # in ledger.ProcessFleet (--fleet_procs).
        self._kill_plan = self._chaos.kills
        self._sigkill_plan = self._chaos.sigkills
        self._stall_plan = self._chaos.stalls

    # ---- replica lifecycle ----------------------------------------------

    def _make_mesh(self, subset):
        if subset is None:
            return None
        from tpukit.mesh import create_mesh

        axes = pick_serve_grid(len(subset), self.cfg.heads, self.serve.slots,
                               paged=self.serve.paged)
        return create_mesh(axes, devices=subset)

    def _place_for(self, mesh, subset_idx: int):
        """Per-replica params placement, cached per subset: N replicas on
        one checkpoint read — placement is pure device_put of the shared
        host copy (the `ckpt_restore` ledger's bytes are paid once;
        `placements` counts the device_put passes). Meshless replicas all
        SHARE one committed copy (params are read-only), so extra
        replicas there place nothing at all."""
        key = -2 if mesh is None else subset_idx
        if key not in self._placed:
            self._placed[key] = place_replica_params(self._params_host, mesh)
            self.placements += 1
        return self._placed[key]

    def _build_replica(self, idx: int, log: bool = True) -> ServeEngine:
        mesh = self._make_mesh(self._subsets[idx])
        eng = ServeEngine(
            self._place_for(mesh, subset_idx=idx), self.cfg, self.serve,
            eos_id=self.eos_id, mesh=mesh, logger=self.logger,
            recorder=self.recorder, replica=idx, tracer=self.tracer,
            metrics=self.metrics,
        )
        eng.stuck_rids = self._chaos.stuck
        self._replicas[idx] = eng
        self._metrics_replicas.add(idx)
        self.replicas_peak = max(self.replicas_peak, len(self._replicas))
        if log:
            self._event("scale_up", replica=idx,
                        devices=len(self._subsets[idx] or []))
        return eng

    def _free_ids(self) -> list[int]:
        return [i for i in range(self.fleet.max_count)
                if i not in self._replicas]

    def _live(self) -> list[ServeEngine]:
        """Admission targets: live, non-draining replicas in id order (so
        max() ties resolve to the lowest id — deterministic routing)."""
        return [e for i, e in sorted(self._replicas.items())
                if i not in self._draining]

    def _event(self, event: str, **kw) -> None:
        if self.logger is not None:
            self.logger.log(kind="fleet_event", event=event, **kw)
        if self.recorder is not None:
            self.recorder.record("fleet_event", event=event, **kw)

    # ---- admission -------------------------------------------------------

    def _admit(self, pending: deque, now: float) -> None:
        """Move arrived requests onto the least-loaded target: most free
        slots, then most free pages, then lowest replica id (`_live`
        ordering + first-maximal `max`). Each engine's batch admits in ONE
        call (the round-14 bucket-grouped batched prefill); paged pool
        pressure returns leftovers, which go back to the queue head in
        arrival order. Round 24: a requeued request additionally waits
        out its jittered backoff gate (`_ready_at` — FIFO is preserved,
        the head simply isn't ready yet), and with a ledger every
        assignment is WRITTEN AHEAD of the engine seeing the request —
        a crash between lease and dispatch replays as a requeue, never a
        lost request. A leftover's assignment is returned (attempt
        un-counted); its stale lease is overwritten at the next assign,
        and replay treats any open lease as in-flight anyway
        (at-least-once assignment, exactly-once completion)."""
        targets = [self.prefill] if self.prefill is not None else self._live()
        if not targets:
            return
        total_free = sum(e.free_slots for e in targets)
        arrived: list[Request] = []
        while (pending and len(arrived) < total_free
               and self._ready_at(pending[0]) <= now):
            arrived.append(pending.popleft())
        if not arrived:
            return
        free = {id(e): e.free_slots for e in targets}
        assign: dict[int, list[Request]] = {id(e): [] for e in targets}
        for req in arrived:
            best = max(targets, key=lambda e: (free[id(e)], e.free_pages))
            assign[id(best)].append(req)
            free[id(best)] -= 1
            if self.tracer is not None:
                self.tracer.emit("route", trace_id(req), rid=req.rid,
                                 t=now, dst=best.replica, replica="router")
        leftovers: list[Request] = []
        for e in targets:
            batch = assign[id(e)]
            if not batch:
                continue
            for req in batch:
                att = self._attempts.get(req.rid, 0) + 1
                self._attempts[req.rid] = att
                if self.ledger is not None:
                    self.ledger.assign(req.rid, e.replica, att, now)
            left = e.admit(batch, now)
            for req in left:
                self._attempts[req.rid] -= 1
            leftovers.extend(left)
        for req in sorted(leftovers, key=lambda r: r.rid, reverse=True):
            pending.appendleft(req)

    def _ready_at(self, req: Request) -> float:
        """When a queued request may admit: its arrival, or its post-
        requeue backoff gate, whichever is later."""
        return max(req.arrival_s, self._not_before.get(req.rid, 0.0))

    def _shed(self, pending: deque, now: float) -> None:
        """Queue-depth backpressure: when more than `max_queue_depth`
        ARRIVED requests are waiting, shed the excess — lowest priority
        first, then latest arrival (highest rid) — each as a NAMED
        `request_rejected` event (and a terminal ledger record, so a
        replayed stream doesn't resurrect it). Shedding at admission
        time, not arrival time, means a queue that drains fast enough
        never rejects."""
        depth = self.fleet.max_queue_depth
        if not depth or len(pending) <= depth:
            return
        arrived = [r for r in pending if r.arrival_s <= now]
        if len(arrived) <= depth:
            return
        shed = sorted(arrived, key=lambda r: (r.priority, -r.rid))
        shed = shed[: len(arrived) - depth]
        drop = {r.rid for r in shed}
        kept = [r for r in pending if r.rid not in drop]
        pending.clear()
        pending.extend(kept)
        for req in sorted(shed, key=lambda r: r.rid):
            self.rejected += 1
            if self.metrics is not None:
                self.metrics.inc("fleet_rejected")
            if self.ledger is not None:
                self.ledger.record_failure(req.rid, "backpressure",
                                           self._attempts.get(req.rid, 0))
            self._event("request_rejected", rid=req.rid,
                        priority=req.priority, reason="backpressure",
                        queue_depth=len(arrived))

    # ---- disaggregated prefill handoff ----------------------------------

    def _handoffs(self, now: float) -> None:
        """Move every prefill-complete worker lane to a decode replica
        with capacity (least-loaded, same rule as admission). A lane with
        no destination WAITS on the worker, holding its pages, until a
        replica frees capacity — prefill work is never discarded."""
        worker = self.prefill
        ready = sorted(
            ((slot, lane) for slot, lane in worker._lanes.items()
             if lane.phase == "decode"),
            key=lambda sl: sl[1].req.rid,
        )
        for slot, lane in ready:
            cands = [e for e in self._live() if e.free_slots > 0]
            if not cands:
                break
            dst = max(cands, key=lambda e: (e.free_slots, e.free_pages))
            if self._adopt(worker, slot, lane, dst, now):
                self.handoffs += 1

    def _adopt(self, worker: ServeEngine, slot: int, lane, dst: ServeEngine,
               now: float) -> bool:
        """One handoff: claim the destination's already-registered prefix
        pages (refcounted — a claimed page can never be reclaimed under
        this reader, however hard the pool is pressed), copy the remaining
        WRITTEN pages device-to-device, and arm the decode lane. Returns
        False (nothing mutated) when the destination pool cannot cover
        the footprint."""
        req, plen = lane.req, lane.prompt_len
        tr = self.tracer
        h0 = tr.now() if tr is not None else 0.0
        p = self.serve.page_size
        written = -(-lane.prefill_end // p)  # pages holding computed K/V
        matched = dst.allocator.lookup_prefix(req.ids, (plen - 1) // p)
        dst.allocator.claim(matched)
        limit = min(plen + req.max_new_tokens, self.serve.width)
        fresh = dst.allocator.alloc(-(-limit // p) - len(matched))
        if fresh is None:
            dst.allocator.release(matched)
            return False
        pages = list(matched) + fresh
        c0 = tr.now() if tr is not None else 0.0
        _copy_pages(worker, dst,
                    lane.pages[len(matched):written],
                    fresh[: written - len(matched)])
        c1 = tr.now() if tr is not None else 0.0
        dst.adopt_prefilled(req, pages, len(matched), lane.admit_s, now,
                            lane.key)
        worker.release_lane(slot)
        if tr is not None:
            tr.emit("handoff", trace_id(req), rid=req.rid, t0=h0,
                    t1=tr.now(), claim_s=c0 - h0, copy_s=c1 - c0,
                    pages=written - len(matched), dst=dst.replica,
                    replica="router")
        return True

    # ---- failure + autoscale --------------------------------------------

    def _maybe_kill(self, rounds: int, now: float) -> None:
        for plan, extra in (
            (self._kill_plan, {}),
            # in-process: a sigkill entry degrades to the simulated kill
            # (there is no process to kill) and SAYS so — real SIGKILL
            # is ledger.ProcessFleet's job (--fleet_procs)
            (self._sigkill_plan, {"signal": "SIGKILL", "simulated": True}),
        ):
            for target in plan.pop(rounds, ()):
                live = sorted(i for i in self._replicas)
                if len(live) <= 1:
                    self._event("kill_skipped", round=rounds,
                                reason="last live replica")
                    continue
                idx = target if target in self._replicas else live[-1]
                self._kill(idx, rounds, now, **extra)

    def _fire_stalls(self, rounds: int) -> None:
        """slow_replica@R:ms — stall the target's HEARTBEAT for ms of
        wall clock without touching the engine: the straggler case the
        liveness check must NOT confuse with death (unless the stall
        outlives replica_timeout, in which case declaring it dead is the
        correct call and the requeue path owns the request)."""
        for stall_s in self._stall_plan.pop(rounds, ()):
            live = sorted(self._replicas)
            if not live:
                continue
            idx = live[-1]
            until = time.time() + stall_s
            self._stalled_until[idx] = max(
                self._stalled_until.get(idx, 0.0), until
            )
            self._chaos.record(dict(fault="slow_replica", round=rounds,
                                    replica=idx, stall_s=stall_s))
            self._event("replica_slow", replica=idx, round=rounds,
                        stall_s=stall_s)

    def _beat(self, rounds: int) -> None:
        """Publish each live replica's heartbeat file (recovery.py's
        one-atomic-file-per-publisher discipline, retry-wrapped like any
        other fleet file I/O). A chaos-stalled replica skips its beat —
        that IS the fault."""
        if self._hb_dir is None:
            return
        wall = time.time()
        # throttle: the loop spins far faster than liveness needs — one
        # beat per ~10 ms keeps heartbeat age resolution well under any
        # sane replica_timeout without an fsync storm
        if wall - self._last_beat_t < 0.01:
            return
        self._last_beat_t = wall
        for idx, eng in sorted(self._replicas.items()):
            if self._stalled_until.get(idx, 0.0) > wall:
                continue
            retry_lib.retry_io(
                recovery_lib.publish_heartbeat, self._hb_dir,
                f"replica-{idx:05d}",
                dict(replica=idx, t=wall, round=rounds,
                     generated=eng.generated_tokens, lanes=eng.live_lanes),
                label="heartbeat",
            )

    def _check_liveness(self, rounds: int, now: float) -> None:
        """Declare heartbeat-silent replicas dead: beat age over
        `replica_timeout` revokes the replica's leases and requeues its
        in-flight requests onto survivors — the round-19 kill path,
        driven by the liveness plane instead of a scripted round."""
        f = self.fleet
        if f.replica_timeout <= 0 or self._hb_dir is None:
            return
        wall = time.time()
        # check at ~4x the timeout's resolution, not every loop spin
        if wall - self._last_live_t < min(f.replica_timeout / 4.0, 0.01):
            return
        self._last_live_t = wall
        beats = recovery_lib.read_heartbeat_dir(self._hb_dir, "replica-")
        for idx in sorted(self._replicas):
            rec = beats.get(f"replica-{idx:05d}")
            if rec is None:
                continue  # not yet published — born this round
            age = wall - float(rec["t"])
            if age <= f.replica_timeout:
                continue
            if len(self._replicas) <= 1:
                self._event("kill_skipped", round=rounds,
                            reason="last live replica")
                continue
            self.replicas_dead += 1
            if self.metrics is not None:
                self.metrics.inc("fleet_replica_dead")
            self._kill(idx, rounds, now, event="replica_dead",
                       reason="heartbeat_timeout", age_s=round(age, 3))

    def _kill(self, idx: int, rounds: int, now: float,
              event: str = "replica_kill", **extra) -> None:
        """Drop replica `idx` mid-flight — the chaos failure model: the
        engine (device state and all) is discarded, its COMPLETED requests
        keep their already-emitted tokens, and its in-flight requests
        re-queue at the queue head with prompt+seed reconstructed from
        the Request (exactly-once output per request: partial tokens were
        never emitted as completions). Round 24 rides liveness deaths
        (`event="replica_dead"`) through the same path, adds the
        `request_retries` budget with jittered-backoff re-admission, and
        publishes the killed engine's completion records to the ledger
        BEFORE the engine is discarded."""
        eng = self._replicas.pop(idx)
        self._draining.discard(idx)
        self._ledger_collect(eng)
        victims = eng.requeue_live()
        self._done.extend(eng.completions)
        # fold the victim's FULL generated count (completed + in-flight
        # partial) into the removed-token tally: the fleet really did
        # generate those partial tokens before discarding them, and the
        # window counter (_fleet_gen - tok0) must stay monotone — folding
        # only the completed tokens would make the post-kill window report
        # NEGATIVE new_tokens. Survivors re-generating the requeued work
        # counts again, honestly: it is work done twice.
        self._gen_removed += eng.generated_tokens
        self._replica_stats[idx] = dict(
            completions=len(eng.completions),
            tokens=sum(c.generated for c in eng.completions),
            occupancy=None, fate="killed" if event == "replica_kill"
            else "dead",
        )
        self.kills += 1
        self.leases_revoked += len(victims)
        kept = self._requeue(victims, idx, now)
        if self.tracer is not None:
            # the requeue event links the killed attempt and the retry
            # under ONE trace id — the same Request object re-queues, so
            # the retry's admit/finish land on the same tree
            for req in kept:
                self.tracer.emit("requeue", trace_id(req), rid=req.rid,
                                 t=now, from_replica=idx, replica="router")
        self._event(event, replica=idx, round=rounds,
                    requeued=len(kept),
                    requeued_rids=[r.rid for r in kept], **extra)
        if self.logger is not None and self.ledger is not None and kept:
            # the durable lease-revocation record: a restarted router can
            # see WHICH leases each death invalidated
            self.logger.log(kind="lease_requeue", from_replica=idx,
                            rids=[r.rid for r in kept],
                            attempts={str(r.rid): self._attempts.get(r.rid, 1)
                                      for r in kept})

    def _requeue(self, victims: list[Request], idx: int,
                 now: float) -> list[Request]:
        """Requeue a dead replica's in-flight requests at the queue head,
        each gated behind a jittered backoff (`retry.backoff_delay` — the
        survivors must not absorb the whole blast in lockstep) and the
        per-request `request_retries` budget: exhaustion is a terminal,
        NAMED failure, not a silent kill->requeue loop."""
        kept: list[Request] = []
        for req in victims:
            n = self._attempts.get(req.rid, 1)
            if n > self.fleet.request_retries:
                self.request_failures += 1
                self._event("request_failed", rid=req.rid, attempts=n,
                            reason="retry_budget")
                if self.metrics is not None:
                    self.metrics.inc("fleet_request_failed")
                if self.ledger is not None:
                    self.ledger.record_failure(req.rid, "retry_budget", n)
                continue
            self._not_before[req.rid] = now + retry_lib.backoff_delay(n)
            kept.append(req)
        self.requeued += len(kept)
        if self.metrics is not None and kept:
            self.metrics.inc("fleet_requeued", len(kept))
        for req in reversed(kept):
            self._pending.appendleft(req)
        return kept

    def _autoscale(self, mean_occ: float, queue_depth: int) -> None:
        f = self.fleet
        live = [i for i in self._replicas if i not in self._draining]
        if (f.scale_up_occupancy and mean_occ >= f.scale_up_occupancy
                and len(live) < f.max_count and self._free_ids()):
            self._build_replica(min(self._free_ids()))
            self.scale_ups += 1
        elif (f.scale_down_occupancy and mean_occ <= f.scale_down_occupancy
                and len(live) > f.min_replicas and queue_depth == 0):
            victim = max(live)
            self._draining.add(victim)
            self.scale_downs += 1
            self._event("scale_down", replica=victim,
                        draining_lanes=self._replicas[victim].live_lanes)

    def _retire_drained(self, now: float) -> None:
        for idx in sorted(self._draining):
            eng = self._replicas[idx]
            if eng.live_lanes:
                continue
            self._retire(idx, eng, now, fate="drained")
            self._event("scale_down_complete", replica=idx)

    def _retire(self, idx: int, eng: ServeEngine, wall: float,
                fate: str) -> None:
        comps = eng.finish(wall)
        self._ledger_collect(eng)
        self._done.extend(comps)
        self._gen_removed += sum(c.generated for c in comps)
        s = eng.last_summary or {}
        self._replica_stats[idx] = dict(
            completions=len(comps),
            tokens=sum(c.generated for c in comps),
            occupancy=s.get("mean_occupancy"), fate=fate,
        )
        del self._replicas[idx]
        self._draining.discard(idx)

    # ---- telemetry -------------------------------------------------------

    def _ledger_collect(self, eng: ServeEngine) -> None:
        """Publish an engine's NEW completions to the durable ledger —
        called after every sync round and before any engine is discarded
        (kill, liveness death, retire), so a crash never loses a finished
        request. The per-engine mark makes this incremental; the ledger's
        check-then-publish makes it exactly-once even when a killed
        replica's work re-completes on a survivor."""
        if self.ledger is None:
            return
        key = id(eng)
        mark = self._ledger_marks.get(key, 0)
        comps = eng.completions
        for c in comps[mark:]:
            self.ledger.complete(c, replica=eng.replica,
                                 attempt=self._attempts.get(c.rid, 1))
        self._ledger_marks[key] = len(comps)

    def _fleet_gen(self) -> int:
        return self._gen_removed + sum(
            e.generated_tokens for e in self._replicas.values()
        )

    def _emit_window(self, now: float, queue_depth: int) -> float:
        """Emit the kind="fleet" window; returns the window's mean
        occupancy (the autoscale signal)."""
        w = self._win
        occ = w["occ"] / max(w["rounds"], 1)
        tok = self._fleet_gen() - w["tok0"]
        wall = now - w["t0"]
        per_replica = {
            str(i): e.generated_tokens
            for i, e in sorted(self._replicas.items())
        }
        rec = dict(
            kind="fleet", window=self._window_idx, rounds=w["rounds"],
            replicas=sorted(self._replicas), draining=sorted(self._draining),
            new_tokens=tok,
            tokens_per_sec=(tok / wall) if wall > 0 else None,
            occupancy=occ, queue_depth=queue_depth,
            requeued=self.requeued - w["req0"],
            per_replica_tokens=per_replica, window_s=wall,
        )
        if self.prefill is not None:
            rec["prefill_lanes"] = self.prefill.live_lanes
            rec["handoffs"] = self.handoffs
        if self.logger is not None:
            self.logger.log(**rec)
        if self.recorder is not None:
            self.recorder.record(
                "fleet", window=self._window_idx, new_tokens=tok,
                occupancy=occ, replicas=len(self._replicas),
            )
        if self.metrics is not None:
            self._metrics_window(rec)
        self._window_idx += 1
        self._win = dict(rounds=0, occ=0.0, tok0=self._fleet_gen(), t0=now,
                         req0=self.requeued)
        return occ

    def _metrics_window(self, rec: dict) -> None:
        """Fleet-level metrics + SLO accounting for one window, derived
        from data the loop already produced (the replica engines observe
        their own per-completion histograms replica-labeled into the
        SAME shared registry)."""
        m = self.metrics
        if rec.get("tokens_per_sec") is not None:
            m.gauge("fleet_tokens_per_sec", rec["tokens_per_sec"])
        m.gauge("fleet_occupancy", rec["occupancy"])
        m.gauge("fleet_queue_depth", rec["queue_depth"])
        m.gauge("fleet_replicas", len(self._replicas))
        if self.slo_accountant is not None:
            # fleet-wide SLO samples: every completion not yet
            # accounted, wherever it lives (live engines or the retired
            # ledger) — exactly-once by rid, the _done dedup invariant
            fresh: list[Completion] = []
            pools = [e.completions for e in self._replicas.values()]
            pools.append(self._done)
            for pool in pools:
                for c in pool:
                    if c.rid not in self._slo_seen_rids:
                        self._slo_seen_rids.add(c.rid)
                        fresh.append(c)
            samples = {
                "e2e": [c.e2e_s for c in fresh],
                "ttft": [max(c.active_s - c.arrival_s, 0.0) for c in fresh],
                "queue_wait": [max(c.admit_s - c.arrival_s, 0.0)
                               for c in fresh],
                "tpot": [c.per_token_s for c in fresh],
            }
            slo_rec = dict(kind="slo", window=self._window_idx,
                           **self.slo_accountant.evaluate(samples))
            if self.logger is not None:
                self.logger.log(**slo_rec)
            if self.recorder is not None:
                self.recorder.record(
                    "slo", window=self._window_idx,
                    overall_compliance=slo_rec["overall_compliance"],
                )
        if self.metrics_dir:
            self._publish_metrics()

    def _publish_metrics(self) -> None:
        """Per-replica snapshot files split from the shared registry by
        label (heartbeat-file discipline: one atomic file per publisher)
        plus the router's process-0 merge beside them. Every touch of the
        shared filesystem rides `retry_io` (round 24) — a transient NFS
        error in a metrics publish must not kill a serving fleet, and
        each failed attempt surfaces as a `kind="retry"` record."""
        wall = time.time()
        count = self.fleet.max_count
        for idx in sorted(self._metrics_replicas):
            retry_lib.retry_io(
                metrics_lib.publish_snapshot, self.metrics_dir, idx,
                self.metrics.filter(replica=idx),
                process_count=count, time_s=wall,
                label="metrics_snapshot",
            )
        merged, meta = retry_lib.retry_io(
            metrics_lib.merge_snapshot_dir, self.metrics_dir,
            process_count=count, label="metrics_merge",
        )
        retry_lib.retry_io(metrics_lib.write_merged, self.metrics_dir,
                           merged, meta=meta, label="metrics_merge")

    def summary(self, wall_s: float) -> dict:
        comps = self._done
        rids = [c.rid for c in comps]
        e2e = sorted(c.e2e_s for c in comps)
        pct = lambda q: (  # noqa: E731
            float(np.percentile(np.asarray(e2e), q)) if e2e else None
        )
        occs = [r["occupancy"] for r in self._replica_stats.values()
                if r.get("occupancy") is not None]
        rec = dict(
            kind="fleet_summary", requests=len(comps),
            generated_tokens=sum(c.generated for c in comps),
            wall_s=wall_s,
            tokens_per_sec=(sum(c.generated for c in comps) / wall_s)
            if wall_s else None,
            replicas_final=len(self._replicas) or sum(
                1 for r in self._replica_stats.values()
                if r["fate"] == "final"
            ),
            replicas_peak=self.replicas_peak,
            scale_ups=self.scale_ups, scale_downs=self.scale_downs,
            kills=self.kills, requeued=self.requeued,
            rejected=self.rejected,
            request_failures=self.request_failures,
            replicas_dead=self.replicas_dead,
            leases_revoked=self.leases_revoked,
            deadline_misses=sum(1 for c in comps if c.reason == "deadline"),
            # the exactly-once invariant, as data: a rid appearing twice
            # means a killed replica's partial work double-emitted
            duplicate_completions=len(rids) - len(set(rids)),
            p50_e2e_s=pct(50), p99_e2e_s=pct(99),
            per_replica=self._replica_stats,
            occupancy_spread=(max(occs) - min(occs)) if len(occs) > 1 else 0.0,
            params_placements=self.placements,
        )
        if self.fleet.disagg_prefill:
            st = self.prefill.allocator.stats
            rec["disagg_prefill"] = dict(
                handoffs=self.handoffs,
                worker_admitted=self.prefill.admitted,
                worker_prefix_hits=st.prefix_hits,
                worker_pages_reused=st.prefix_pages_reused,
            )
        if self.tracer is not None:
            # fleet-wide per-phase latency view over every completed
            # request's span tree (killed-replica work included — the
            # shared tracer outlives its emitters)
            done_rids = {c.rid for c in comps}
            trees = [t for t in trace_lib.build_trees(self.tracer.snapshot())
                     if t["rid"] in done_rids]
            rec["phase_p50"], rec["phase_p99"] = trace_lib.phase_stats(trees)
            rec["trace_complete"] = trace_lib.completeness(trees)
            # per-ring evictions (round 22): a saturated ring silently
            # reads as a complete history otherwise — report.py warns
            # when nonzero
            by_rep = self.tracer.dropped_by_replica
            rec["trace_dropped"] = sum(by_rep.values())
            rec["trace_dropped_by_replica"] = {
                str(k): v for k, v in sorted(by_rep.items(), key=str)
            }
        if self.slo_accountant is not None:
            rec["slo_overall_compliance"] = (
                self.slo_accountant.overall_compliance()
            )
        if self.ledger is not None:
            rec["ledger"] = dict(
                completed=len(self.ledger.completions()),
                replayed=self._replayed,
                duplicates=self.ledger.duplicates(),
            )
        return rec

    # ---- the loop --------------------------------------------------------

    def run(self, requests, max_wall_s: float | None = None) -> list[Completion]:
        """Serve `requests` across the fleet to completion; returns ALL
        completions in finish order. The loop per iteration: fire any
        scheduled kill/stall, check heartbeat liveness, publish beats,
        shed over-depth queue, admit ready requests least-loaded, advance
        prefill (worker chunks + handoffs, or per-replica chunks),
        DISPATCH every replica's decode quantum (async — disjoint subsets
        overlap), then sync each, publish fresh completions to the ledger,
        and retire finished lanes. Fleet windows and the autoscale check
        run every `FleetConfig.window_steps` dispatch rounds. With a
        `fleet_dir`, the request stream is durable: a restarted router
        passed the same stream replays the ledger and serves only the
        not-yet-completed frontier."""
        if self.ledger is not None:
            requests, done_recs = self.ledger.open_stream(requests)
            self._replayed = len(done_recs)
            if self._replayed:
                self._event("ledger_replay", completed=self._replayed,
                            remaining=len(requests))
        self._pending = deque(
            sorted(requests, key=lambda r: (r.arrival_s, r.rid))
        )
        pending = self._pending
        # the serving chaos engine is process-global for the run so the
        # ledger's raw I/O helpers reach it through the same
        # chaos.maybe_io_fault hook the checkpoint sites use
        prev_chaos = chaos_lib.installed()
        chaos_lib.install(self._chaos)
        try:
            return self._run_loop(pending, max_wall_s)
        finally:
            chaos_lib.install(prev_chaos)

    def _run_loop(self, pending: deque,
                  max_wall_s: float | None) -> list[Completion]:
        # reset every engine's span epoch to the FLEET run start so the
        # construction->run gap lands nowhere (the engine.run discipline)
        for eng in self._replicas.values():
            eng.spans.epoch()
        if self.prefill is not None:
            self.prefill.spans.epoch()
        t0 = time.perf_counter()
        if self.tracer is not None:
            self.tracer.set_epoch(t0)
            for r in pending:
                self.tracer.emit("enqueue", trace_id(r), rid=r.rid,
                                 t=r.arrival_s, replica="router")
        self._win["t0"] = 0.0
        rounds = 0
        while pending or self._any_lanes():
            now = time.perf_counter() - t0
            if max_wall_s is not None and now > max_wall_s:
                raise TimeoutError(
                    f"fleet run exceeded max_wall_s={max_wall_s} with "
                    f"{len(pending)} pending and "
                    f"{sum(e.live_lanes for e in self._replicas.values())} "
                    f"live lanes"
                )
            self._maybe_kill(rounds, now)
            self._fire_stalls(rounds)
            self._check_liveness(rounds, now)
            self._beat(rounds)
            self._shed(pending, now)
            self._admit(pending, now)
            if self.prefill is not None:
                self.prefill.poll_prefill(time.perf_counter() - t0)
                self._handoffs(time.perf_counter() - t0)
            else:
                for eng in list(self._replicas.values()):
                    eng.poll_prefill(time.perf_counter() - t0)
            # dispatch ALL replicas' quanta before syncing any: the
            # dispatches are async, so disjoint device subsets decode
            # concurrently while the host walks the list
            dispatched = [e for e in self._replicas.values()
                          if e.dispatch_decode()]
            if not dispatched:
                if not self._any_lanes() and pending:
                    wait = self._ready_at(pending[0]) - now
                    if wait > 0:
                        time.sleep(min(wait, 0.05))
                continue
            rounds += 1
            # occupancy is sampled at DISPATCH time (lanes occupied during
            # the quantum just issued) — post-sync, a lane that finished
            # mid-quantum would read as idle and a saturated replica as
            # half-busy, starving the scale-up signal
            slots = sum(e.serve.slots for e in self._replicas.values())
            decoding = sum(e.decoding_lanes for e in self._replicas.values())
            snow = time.perf_counter() - t0
            for eng in dispatched:
                eng.sync(snow)
                self._ledger_collect(eng)
            self._win["rounds"] += 1
            self._win["occ"] += decoding / max(slots, 1)
            if self._win["rounds"] >= self.fleet.window_steps:
                occ = self._emit_window(snow, len(pending))
                self._autoscale(occ, len(pending))
            self._retire_drained(time.perf_counter() - t0)
        wall = time.perf_counter() - t0
        if self._win["rounds"]:
            self._emit_window(wall, 0)
        for idx, eng in sorted(self._replicas.items()):
            self._retire(idx, eng, wall, fate="final")
        if self.logger is not None:
            for ev in self._chaos.drain_fired():
                self.logger.log(kind="chaos", **ev)
        rec = self.last_summary = self.summary(wall)
        if self.logger is not None:
            self.logger.log(**rec)
        if self.recorder is not None:
            self.recorder.record(
                "fleet_summary", requests=rec["requests"],
                tokens_per_sec=rec["tokens_per_sec"],
                requeued=rec["requeued"], kills=rec["kills"],
            )
        if self.tracer is not None:
            # one flush for the whole fleet: events + span trees into the
            # JSONL (replica engines share this tracer and skip their own
            # flush — see ServeEngine.finish)
            trace_lib.flush_to_logger(
                self.tracer, self.logger,
                trace_lib.build_trees(self.tracer.snapshot()),
            )
        if self.metrics is not None:
            # one metrics epilogue for the whole fleet (replica engines
            # share this registry and skip their own — ServeEngine.finish
            # only emits when replica is None): the kind="metrics"
            # summary row plus the final snapshot publish/merge
            rec_m = dict(kind="metrics", source="fleet",
                         **self.metrics.summary())
            if self.logger is not None:
                self.logger.log(**rec_m)
            if self.recorder is not None:
                self.recorder.record(
                    "metrics", source="fleet",
                    hists=len(rec_m["hists"]),
                    tokens=self.metrics.sum_counter("serve_tokens"),
                )
            if self.metrics_dir:
                self._publish_metrics()
        self._done.sort(key=lambda c: c.done_s)
        return self._done

    def _any_lanes(self) -> bool:
        if any(e.live_lanes for e in self._replicas.values()):
            return True
        return self.prefill is not None and self.prefill.live_lanes > 0


def _copy_pages(src: ServeEngine, dst: ServeEngine, src_ids, dst_ids) -> None:
    """The device-to-device page copy of the disaggregated handoff, spelled
    ONCE: gather the source pool's page rows (`paged.extract_pages`), move
    the block across device subsets with one `jax.device_put` at the
    destination pool's layout, scatter into the destination pool
    (`paged.insert_pages`). Covers K/V pools and (int8) scale sidecars
    alike. Ids pad to the next power of two so the traced-id programs
    compile log-many times: source pads by REPEATING the last id
    (re-extracting a page is idempotent), destination pads with 0 — the
    null page, whose contents are garbage by design (write-safety
    invariant 2)."""
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    if not src_ids:
        return
    n = 1 << (len(src_ids) - 1).bit_length()
    s = np.asarray(list(src_ids) + [src_ids[-1]] * (n - len(src_ids)),
                   np.int32)
    d = np.asarray(list(dst_ids) + [0] * (n - len(dst_ids)), np.int32)
    for key, spec in (("k", dst._pool_spec), ("v", dst._pool_spec),
                      ("ks", dst._scale_spec), ("vs", dst._scale_spec)):
        if key not in src.cache:
            continue
        block = paged_lib.extract_pages(src.cache[key], src._place(s, P()))
        if dst.mesh is not None:
            block = jax.device_put(block, NamedSharding(dst.mesh, spec))
        else:
            block = jax.device_put(block)
        dst.cache[key] = paged_lib.insert_pages(
            dst.cache[key], dst._place(d, P()), block
        )
