"""Speculative decoding: draft-and-verify on the slot engine (round 17).

ROADMAP open item 3. Vanilla decode is bounded by one target-model forward
per token per slot (the round-14 tick): latency is model depth per token,
whatever the batch. Speculation breaks that bound with two moves:

  - a **draft proposer** guesses k candidate tokens per active slot per
    scheduler quantum — either a small tpukit GPT draft model with its own
    KV ring (`draft_propose`), or **self-speculation** with no second model
    at all (`NGramProposer`: prompt-lookup / n-gram continuation of the
    slot's own history — near-free, and very effective on repetitive
    streams);
  - the target model scores all k+1 positions in ONE batched forward
    (`verify_step`): the k-token window per slot is exactly the
    "mini-prefill" chunk shape the per-row-cursor cached attention
    (`gpt.forward_cached` with a vector `start`) already compiles for
    chunked prefill — one dispatch verifies what vanilla decode needed
    k+1 dispatch-sequential ticks to produce.

**Distribution exactness** (the whole point — speculation must be an
optimization, never a model change):

  - temperature == 0: a draft token is accepted iff it equals the
    target's argmax at its position; the first mismatch is replaced by
    the target argmax. Greedy output is therefore TOKEN-IDENTICAL to
    vanilla decode by construction (asserted engine-vs-engine in
    tests/test_spec.py).
  - temperature > 0: standard rejection sampling (Leviathan et al. /
    Chen et al., PAPERS.md): accept draft token d with probability
    min(1, p(d)/q(d)) where p is the TARGET distribution and q the
    proposal; on the first rejection sample from the residual
    norm(max(p - q, 0)); if every draft survives, sample a bonus token
    from p at the next position. Marginally each emitted token is an
    exact p-sample:  P(x) = q(x)·min(1, p(x)/q(x)) +
    (1 - Σ_y q(y)·min(1, p(y)/q(y)))·residual(x) = p(x).
    Deterministic proposers (n-gram) are the one-hot-q special case:
    accept with probability p(d), residual = p with d zeroed.

  The target distribution p is built with `sampling._adjust_logits` —
  the SAME temperature/top-k transform `_sample_next` draws from — and
  the whole acceptance computation lives in ONE spelling
  (`_accept_prefix`) shared by the engine's batched verify (vmapped over
  slots) and the serial test reference (`reference_spec_decode`), the
  round-14 `_sample_next` discipline applied to speculation: parity is
  the bit-for-bit agreement of this one function across call sites.

**Why KV rollback is free** (ring cache): the verify forward writes K/V
for positions `[cur-1, cur-1+k]` BEFORE attending, and attention reads
only `key_pos <= q_pos` — so rejected positions hold garbage K/V that is
above the advanced cursor, unreachable by the causal window, and
REWRITTEN by the next quantum's verify before anything attends to it:
exactly the round-14 stale-tail invariant (serve/decode.py module
docstring), now load-bearing for rollback. The same argument covers the
draft model's own ring, with one extra care: a quantum can leave the
draft ring missing K/V for up to TWO trailing emitted tokens (the k-th
accepted proposal and the bonus sample — the draft's own ticks stop one
position short of its last proposal), so `draft_propose` opens with a
2-token catch-up window re-forwarding `buf[cur-2], buf[cur-1]` before
proposing, overwriting whatever rejected proposals (or a previous slot
occupant) left behind — "rollback" is a cursor rewind plus that fixed-
width rewrite, no data movement. (A paged draft
cache would be a block-table-row truncate for the same reason, but the
multi-token verify write-back needs position-granular masked pool writes
the paged `write_pages` contract — page-aligned whole pages — does not
cover, so spec requires the ring cache this round; `ServeConfig` rejects
`draft` + `page_size` with a named error. DESIGN.md §16.)

The ring is over-allocated by `spec_k` scratch positions
(`width + spec_k`): a lane whose cursor sits near the buffer end still
writes its full k+1 verify window without `dynamic_update_slice`'s
start-clamping sliding the chunk DOWN over valid history. Scratch
positions sit above every lane's limit, so they are never appended,
never attended by an accepted query, and rewritten like any stale tail.

Per-step comm has the same closed form as the vanilla step widened by
the verify window: `decode.decode_step_comm(..., verify_tokens=k+1)`
prices the compiled `verify_step` under the TP serving grid exactly
(same collective COUNT as one decode tick — the speculation win in comm
terms: k+1 tokens of progress per collective round-trip), audited
through hlolint's comm-plan rule (`tools/hlolint.py --world 8`,
spec_verify world).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from tpukit.model import gpt
from tpukit.sampling import _adjust_logits, _sample_next

# Salted sub-streams of the per-request PRNG key: the accept uniforms and
# the residual/bonus draw fold a salt on top of the position fold so they
# never collide with `_sample_next`'s unsalted `fold_in(key, pos)` — which
# the DRAFT model's own sampling uses verbatim (it proposes exactly what a
# vanilla decode of the draft would emit at that position).
_SALT_ACCEPT = 0x5AC
_SALT_FIX = 0x5AF

_TINY = 1e-30  # guards p/q ratios and log(0); never changes an accept


def _accept_prefix(logits, draft, q_probs, draft_len, key, cursor,
                   temperature: float, top_k: int):
    """THE acceptance spelling — one slot's rejection-sampling pass over
    one verify window. `logits [k+1, V]` f32 target logits (position j
    predicts the token at `cursor + j`), `draft [k]` proposed tokens,
    `q_probs [k, V]` the proposal distribution per position (one-hot rows
    for deterministic proposers), `draft_len` in `[0, k]` (positions
    `>= draft_len` are padding, never accepted), `key [2]` the request's
    PRNG key, `cursor` the slot's logical position.

    Returns `(accepted, tokens)`: `accepted` is the accepted-prefix
    length (`<= draft_len`), `tokens [k+1]` carries the accepted draft
    tokens in `[0, accepted)` and the corrected / bonus target sample at
    index `accepted` (entries beyond are unspecified). The k=0 / all-
    padding degenerate emits exactly one target sample — a vanilla step.

    The engine vmaps this over slots; the serial test reference calls it
    on one row — bit-for-bit the same math is the parity guarantee
    (module docstring). Draw streams: accept uniforms at
    `fold_in(fold_in(key, cursor+i), _SALT_ACCEPT)`, the correction at
    `fold_in(fold_in(key, cursor+accepted), _SALT_FIX)` — position-keyed,
    so a fixed seed reproduces regardless of quantum boundaries."""
    k = draft.shape[0]
    i = jnp.arange(k, dtype=jnp.int32)
    if temperature > 0.0:
        adj = _adjust_logits(logits, temperature, top_k)  # [k+1, V]
        p = jax.nn.softmax(adj, axis=-1)
        u = jax.vmap(
            lambda pos: jax.random.uniform(
                jax.random.fold_in(jax.random.fold_in(key, pos), _SALT_ACCEPT)
            )
        )(cursor + i)
        p_d = jnp.take_along_axis(p[:k], draft[:, None], axis=1)[:, 0]
        q_d = jnp.take_along_axis(q_probs, draft[:, None], axis=1)[:, 0]
        # accept iff u < min(1, p/q)  <=>  u * q < p (u ~ U[0,1))
        ok = (i < draft_len) & (u * jnp.maximum(q_d, _TINY) < p_d)
        accepted = jnp.sum(jnp.cumprod(ok.astype(jnp.int32)))
        p_next = p[accepted]  # [V] — target dist at the correction slot
        rejected = accepted < draft_len
        q_row = q_probs[jnp.minimum(accepted, k - 1)]
        resid = jnp.maximum(p_next - q_row, 0.0)
        rsum = jnp.sum(resid)
        # all-accepted -> bonus from p; rejected -> residual correction.
        # A numerically-empty residual (p == q to the ulp) falls back to
        # p itself — still an exact p-sample, since rejection there has
        # probability ~0 anyway.
        dist = jnp.where(rejected & (rsum > 0.0), resid / jnp.maximum(rsum, _TINY), p_next)
        fix = jax.random.categorical(  # lint: allow(sampling-spelling): the rejection-sampling CORRECTION draw — from the residual max(p-q,0), not the model distribution _sample_next owns, on the salted _SALT_FIX stream so it can never collide with _sample_next's unsalted position fold
            jax.random.fold_in(
                jax.random.fold_in(key, cursor + accepted), _SALT_FIX
            ),
            jnp.where(dist > 0.0, jnp.log(jnp.maximum(dist, _TINY)), -jnp.inf),
        )
    else:
        am = jnp.argmax(logits, axis=-1)  # [k+1]
        ok = (i < draft_len) & (draft == am[:k])
        accepted = jnp.sum(jnp.cumprod(ok.astype(jnp.int32)))
        fix = am[accepted]
    dpad = jnp.concatenate([draft, draft[-1:]])
    tokens = jnp.where(jnp.arange(k + 1) < accepted, dpad, fix)
    return accepted, tokens.astype(jnp.int32)


def _verify_body(params, cfg: gpt.GPTConfig, buf, cache, cursors, active,
                 limits, keys, draft, draft_q, draft_len, eos_id: int,
                 temperature: float, top_k: int, k: int,
                 onehot_q: bool, mesh):
    """The verify quantum's traced body — ONE spelling shared by
    `verify_step` (external draft: the draft model, or a host-side test
    proposer) and `spec_ngram_step` (fused on-device self-speculation).
    See `verify_step` for the contract."""
    n, total = buf.shape
    read = jnp.clip(cursors - 1, 0, total - 1)
    last_tok = jnp.take_along_axis(buf, read[:, None], axis=1)
    toks = jnp.concatenate([last_tok, draft.astype(buf.dtype)], axis=1)
    pos = read[:, None] + jnp.arange(k + 1, dtype=jnp.int32)[None, :]
    logits, cache = gpt.forward_cached(params, cfg, toks, pos, cache, read)
    lg = logits.astype(jnp.float32)  # [N, k+1, V]
    if mesh is not None and "model" in mesh.axis_names:
        # The decode step's logits constraint, k+1 wide: ONE all-gather of
        # the vocab-sharded head output per quantum at a size the closed
        # form prices exactly (decode.decode_step_comm, verify_tokens).
        batch_axis = "data" if "data" in mesh.axis_names else None
        lg = jax.lax.with_sharding_constraint(
            lg, NamedSharding(mesh, P(batch_axis, None, None))
        )
    if onehot_q:
        q = jax.nn.one_hot(draft, lg.shape[-1], dtype=jnp.float32)
    else:
        q = draft_q
    accepted, cand = jax.vmap(
        partial(_accept_prefix, temperature=temperature, top_k=top_k)
    )(lg, draft, q, draft_len, keys, cursors)

    # Per-token emission gates, vectorized over the candidate window —
    # tick-for-tick the vanilla `_advance` semantics: a token appends iff
    # the lane is active, it is within the accepted prefix, its position
    # fits below the limit, and no earlier candidate was EOS; the first
    # EOS inside the appendable window freezes the lane WITHOUT being
    # appended (reference stop-before-append), and a lane whose cursor
    # reaches its limit freezes with reason "length" exactly as vanilla.
    j = jnp.arange(k + 1, dtype=jnp.int32)[None, :]
    can = active[:, None] & (j <= accepted[:, None])
    fits = (cursors[:, None] + j) < limits[:, None]
    is_eos = cand == eos_id
    eos_before = jnp.cumsum(is_eos.astype(jnp.int32), axis=1) - is_eos.astype(jnp.int32)
    append = can & fits & (eos_before == 0) & ~is_eos
    eos_hit = jnp.any(can & fits & (eos_before == 0) & is_eos, axis=1)
    n_app = jnp.sum(append.astype(jnp.int32), axis=1)

    # One-hot-select buffer write (the decode-step rule: a batched scatter
    # drags s32 index plumbing through GSPMD; the masked select is
    # comm-free). `append` is a contiguous prefix of the window (every
    # gate is prefix-monotone), so the write range is [cursor, cursor+n).
    col = jax.lax.broadcasted_iota(jnp.int32, (n, total), 1)
    rel = col - cursors[:, None]
    sel = (rel >= 0) & (rel < n_app[:, None])
    vals = jnp.take_along_axis(cand, jnp.clip(rel, 0, k), axis=1)
    buf = jnp.where(sel, vals.astype(buf.dtype), buf)
    new_cursors = cursors + n_app
    new_active = active & ~eos_hit & (new_cursors < limits)
    return buf, cache, new_cursors, new_active, accepted, n_app


# No donation — the serve-path rule (decode.decode_step note: persistent-
# cache deserialization of donated executables mis-aliases on this jaxlib).
@partial(
    jax.jit,
    static_argnames=("cfg", "k", "eos_id", "temperature", "top_k",
                     "onehot_q", "mesh"),
)
def verify_step(params, cfg: gpt.GPTConfig, buf, cache, cursors, active,
                limits, keys, draft, draft_q, draft_len, eos_id: int,
                temperature: float = 0.0, top_k: int = 0, *, k: int,
                onehot_q: bool = False, mesh=None):
    """One speculative quantum for every slot: score the k+1-token verify
    window `[last emitted, d_0 .. d_{k-1}]` in ONE batched forward against
    the KV ring (per-row vector cursors — the chunked-prefill shape),
    accept a per-slot prefix by `_accept_prefix`, and append the accepted
    tokens plus the corrected/bonus sample under EXACTLY the vanilla
    per-token gates (stop before appending EOS, stop at the limit,
    inactive lanes frozen). Returns
    `(buf, cache, cursors, active, accepted, appended)` — the last two
    `[N]` i32 for telemetry (draft tokens accepted by the test; tokens
    actually appended incl. the correction).

    `draft [N, k]` / `draft_len [N]` come from the proposer;
    `draft_q [N, k, V]` is the proposal distribution (pass None with
    `onehot_q=True` for deterministic proposers — the one-hot rows are
    built on device, saving the H2D). Rejected positions need no cache
    rollback (module docstring); inactive lanes re-forward garbage into
    positions above their frozen cursors, unreachable like any stale
    tail. Under a TP `mesh` the k+1 sample logits are pinned
    model-replicated — the widened twin of the decode step's one
    deliberate constraint — so the compiled collectives match
    `decode.decode_step_comm(..., verify_tokens=k+1)` exactly."""
    return _verify_body(params, cfg, buf, cache, cursors, active, limits,
                        keys, draft, draft_q, draft_len, eos_id,
                        temperature, top_k, k, onehot_q, mesh)


def _ngram_propose_row(h, cur, *, k: int, max_ngram: int):
    """Device twin of `NGramProposer.propose` for ONE slot's buffer row
    `h [W]` at cursor `cur` — bit-for-bit the same proposal (asserted in
    tests/test_spec.py over random and crafted histories): longest suffix
    length first (`max_ngram` down to 1; a static unrolled loop), most
    recent earlier occurrence, then the periodic-wrap continuation
    `h[cur - s + (i mod s)]` where `s` is the implied period. Returns
    `(draft [k] i32, dlen scalar i32)`, dlen == 0 when no n-gram recurs
    (the k=0 degenerate — verify falls back to a vanilla step)."""
    w = h.shape[0]
    pos = jnp.arange(w, dtype=jnp.int32)
    # The whole match is spelled as static shifts + one-hot masked sums —
    # NO dynamic gathers: a gather indexed by the data-sharded cursor
    # drags s32 index-plumbing all-gathers through GSPMD (the round-14
    # decode buf scatter class, now a named hlolint rule), while shifts
    # and selects partition comm-free. shifts[i][j] == h[j + i] (the pad
    # tail is never consulted: matches require j < cur - n <= w - n).
    shifts = [
        h if i == 0
        else jnp.concatenate([h[i:], jnp.zeros((i,), h.dtype)])
        for i in range(max_ngram)
    ]
    found_n = jnp.int32(0)
    found_j = jnp.int32(-1)
    for n in range(max_ngram, 0, -1):  # longest first, static unroll
        # an EARLIER occurrence: j < cur - n (continuation has at least
        # one in-history token), and the suffix itself must fit (n < cur)
        ok = (pos < cur - n) & (n <= cur - 1)
        for i in range(n):
            # suffix token h[cur - n + i] as a one-hot masked sum
            sfx_i = jnp.sum(jnp.where(pos == cur - n + i, h, 0))
            ok = ok & (shifts[i] == sfx_i)
        j_n = jnp.max(jnp.where(ok, pos, -1))
        take = (found_j < 0) & (j_n >= 0)
        found_n = jnp.where(take, n, found_n)
        found_j = jnp.where(take, j_n, found_j)
    s = jnp.maximum((cur - found_n) - found_j, 1)  # implied period, >= 1
    idx = cur - s + (jnp.arange(k, dtype=jnp.int32) % s)  # all < cur
    draft = jnp.sum(
        jnp.where(pos[None, :] == idx[:, None], h[None, :], 0), axis=1
    )
    dlen = jnp.where(found_j >= 0, k, 0).astype(jnp.int32)
    return draft.astype(jnp.int32), dlen


# No donation — serve-path rule (see verify_step).
@partial(
    jax.jit,
    static_argnames=("cfg", "k", "max_ngram", "eos_id", "temperature",
                     "top_k", "mesh"),
)
def spec_ngram_step(params, cfg: gpt.GPTConfig, buf, cache, cursors, active,
                    limits, keys, eos_id: int, temperature: float = 0.0,
                    top_k: int = 0, *, k: int, max_ngram: int = 3,
                    mesh=None):
    """The FUSED self-speculation quantum: on-device n-gram proposal
    (`_ngram_propose_row`, vmapped — pure per-slot tensor ops, ZERO
    collectives and no measurable compute next to the forward) feeding
    the verify body in the SAME compiled program. One dispatch and one
    host sync per quantum — exactly the vanilla decode step's host
    rhythm, which is what makes self-speculation a strict win on
    repetitive streams instead of trading a forward for two host round
    trips (a host-side proposer pays buf D2H + draft H2D + a second
    dispatch every quantum). Returns the `verify_step` tuple plus the
    per-slot proposal length `dlen [N]` for telemetry. This is the
    program the hlolint `spec_verify` world audits — the comm plan is
    `decode_step_comm(verify_tokens=k+1)` unchanged, because the n-gram
    match reads only the data-sharded buf/cursors."""
    draft, dlen = jax.vmap(
        partial(_ngram_propose_row, k=k, max_ngram=max_ngram)
    )(buf, cursors)
    out = _verify_body(params, cfg, buf, cache, cursors, active, limits,
                       keys, draft, None, dlen, eos_id, temperature, top_k,
                       k, True, mesh)
    return out + (dlen,)


# No donation — serve-path rule (see verify_step).
@partial(
    jax.jit,
    static_argnames=("cfg", "k", "temperature", "top_k"),
)
def draft_propose(params, cfg: gpt.GPTConfig, buf, cache, cursors, keys,
                  *, k: int, temperature: float = 0.0, top_k: int = 0):
    """The draft-model proposer: k tokens per slot from the draft's OWN
    KV ring, autoregressively — each tick forwards the previous token at
    position `cursor - 1 + i` and samples the next with `_sample_next`
    under the engine's temperature/top-k and the slot's request key (the
    unsalted `fold_in(key, pos)` — the draft proposes exactly what a
    vanilla decode of the draft model would emit, one spelling).
    Returns `(draft [N, k] i32, q_probs [N, k, V] f32, cache)`; `q_probs`
    rows are `softmax(_adjust_logits(...))` at temperature > 0 and
    one-hot at the argmax for greedy — the distribution the verify
    step's acceptance test corrects against.

    The pass opens with a TWO-token catch-up window (`buf[cur-2],
    buf[cur-1]` at their own positions) rather than re-forwarding just
    the last emitted token: after an all-accept-plus-bonus quantum the
    draft ring is missing K/V for BOTH trailing emitted tokens — the
    k-th proposal (the last position its own ticks forwarded was k-1)
    and the bonus sample — and a 1-token catch-up would leave the
    earlier of the two permanently unwritten, silently attending
    whatever a previous slot occupant left there. Every other quantum
    shape leaves at most those same two trailing positions stale, so
    the 2-wide window restores the invariant exactly; the serial
    reference mirrors the same spelling, which is what makes engine ==
    reference bit-for-bit (tests/test_spec.py)."""
    n, total = buf.shape
    read = jnp.clip(cursors - 1, 0, total - 1)
    prev = jnp.clip(cursors - 2, 0, total - 1)
    t2 = jnp.concatenate(
        [jnp.take_along_axis(buf, prev[:, None], axis=1),
         jnp.take_along_axis(buf, read[:, None], axis=1)], axis=1
    ).astype(jnp.int32)
    pos2 = jnp.stack([prev, read], axis=1).astype(jnp.int32)
    logits2, cache = gpt.forward_cached(params, cfg, t2, pos2, cache, prev)
    v = cfg.padded_vocab_size

    def sample(last, i):
        """Proposal i from its f32 logits row: token + q-distribution."""
        if temperature > 0.0:
            adj = _adjust_logits(last, temperature, top_k)
            qp = jax.nn.softmax(adj, axis=-1)
            nxt = jax.vmap(
                partial(_sample_next, temperature=temperature, top_k=top_k)
            )(last, cursors + i, keys)
        else:
            nxt = jnp.argmax(last, axis=-1)
            qp = jax.nn.one_hot(nxt, v, dtype=jnp.float32)
        return nxt.astype(jnp.int32), qp

    d0, q0 = sample(logits2[:, -1].astype(jnp.float32), 0)
    toks0 = jnp.zeros((n, k), jnp.int32).at[:, 0].set(d0)
    qs0 = jnp.zeros((n, k, v), jnp.float32).at[:, 0].set(q0)

    def tick(i, carry):
        tok, cache, toks, qs = carry
        p = read + i
        logits, cache = gpt.forward_cached(
            params, cfg, tok[:, None], p[:, None].astype(jnp.int32), cache, p
        )
        nxt, qp = sample(logits[:, -1].astype(jnp.float32), i)
        toks = jax.lax.dynamic_update_slice(toks, nxt[:, None], (0, i))
        qs = jax.lax.dynamic_update_slice(qs, qp[:, None, :], (0, i, 0))
        return nxt, cache, toks, qs

    _, cache, toks, qs = jax.lax.fori_loop(1, k, tick, (d0, cache, toks0, qs0))
    return toks, qs, cache


class NGramProposer:
    """Self-speculation: prompt-lookup / n-gram drafting — no second
    model. For a slot with token history `h[:cur]`, find the most recent
    earlier occurrence of the longest current suffix (length
    `max_ngram` down to 1) and propose the `k` tokens that followed it.
    Deterministic (reproducible per stream), near-free on the host, and
    highly effective when generation is repetitive — which both the
    synthetic repetitive stream and small-model greedy loops are.

    The proposal distribution is the one-hot at each proposed token
    (`onehot_q=True` in `verify_step`): acceptance probability collapses
    to p(d) and the residual to p with d zeroed — still an exact
    p-sample marginally (module docstring)."""

    def __init__(self, k: int, max_ngram: int = 3):
        if k < 1 or max_ngram < 1:
            raise ValueError(
                f"NGramProposer needs k >= 1 and max_ngram >= 1 "
                f"(got k={k}, max_ngram={max_ngram})"
            )
        self.k = k
        self.max_ngram = max_ngram

    def propose(self, history) -> list[int]:
        """Up to `k` proposed continuation tokens for one slot's history
        (empty when no n-gram of any length recurs): the most recent
        earlier occurrence of the longest matching suffix (length
        `max_ngram` down to 1) names an implied repetition period
        `s = suffix_start - occurrence_start`, and the proposal walks
        the history forward from the occurrence's continuation, WRAPPING
        back by `s` past the end — so a period-p loop proposes the full
        k tokens however small p is (the most recent occurrence always
        sits one period from the end; without the wrap a proposal could
        never exceed p tokens). For a periodic tail the wrap is exactly
        chained re-lookup, at O(k) instead of O(k·len) after the one
        match; histories are bucket-bounded and the suffix scan is
        numpy-vectorized per candidate length."""
        h = np.asarray(history)
        m = len(h)
        for n in range(min(self.max_ngram, m - 1), 0, -1):
            suffix = h[m - n:]
            # candidate start positions of an EARLIER occurrence (the
            # continuation must have at least one token inside history)
            starts = np.flatnonzero(h[: m - n] == suffix[0])
            for j in starts[::-1]:  # most recent first
                if j + n < m and np.array_equal(h[j : j + n], suffix):
                    s = (m - n) - j  # the implied repetition period
                    out = []
                    for i in range(self.k):
                        pos = j + n + i
                        while pos >= m:
                            pos -= s
                        out.append(int(h[pos]))
                    return out
        return []


def reference_spec_decode(params, cfg: gpt.GPTConfig, ids, max_new: int,
                          eos_id: int, *, k: int, draft: str = "ngram",
                          draft_params=None, draft_cfg=None,
                          temperature: float = 0.0, top_k: int = 0,
                          seed: int = 0, max_ngram: int = 3):
    """Serial ONE-REQUEST speculative decode — the independent spelling
    the engine parity tests pin against (tests/test_spec.py): a plain
    Python loop over scalar-start `gpt.forward_cached` calls (the
    round-14 serial-cached decode layout) with the SAME `_accept_prefix`
    acceptance math, the same proposers, and the same position-keyed
    draw streams. A fixed seed must reproduce the engine's batched
    output token-for-token for the same request. Returns the emitted
    ids (prompt + generated) as an int array."""
    ids = np.asarray(ids, np.int32)
    plen = len(ids)
    total = plen + max_new + k  # + the verify scratch tail (module doc)
    buf = np.zeros((total,), np.int32)
    buf[:plen] = ids
    key = jnp.asarray(np.asarray(jax.random.PRNGKey(seed)))
    cache = gpt.init_kv_cache(cfg, 1, total)
    if plen > 1:
        p = jnp.arange(plen - 1, dtype=jnp.int32)[None, :]
        _, cache = gpt.forward_cached(
            params, cfg, jnp.asarray(buf[None, : plen - 1]), p, cache, 0
        )
    proposer = None
    d_cache = None
    if draft == "ngram":
        proposer = NGramProposer(k, max_ngram=max_ngram)
    elif draft == "model":
        d_cache = gpt.init_kv_cache(draft_cfg, 1, total)
        if plen > 1:
            p = jnp.arange(plen - 1, dtype=jnp.int32)[None, :]
            _, d_cache = gpt.forward_cached(
                draft_params, draft_cfg,
                jnp.asarray(buf[None, : plen - 1]), p, d_cache, 0,
            )
    else:
        raise ValueError(f"draft must be 'ngram' or 'model', got {draft!r}")

    cur = plen
    limit = min(plen + max_new, total - k)  # == plen + max_new
    active = cur < limit
    while active:
        if draft == "ngram":
            prop = proposer.propose(buf[:cur])
            dlen = len(prop)
            d = np.zeros((k,), np.int32)
            d[:dlen] = prop
            d = jnp.asarray(d)
            q = None
        else:
            # the serial twin of draft_propose: the 2-token catch-up
            # window first (closing the all-accept trailing-K/V gap the
            # same way the batched spelling does), then one tick per
            # remaining proposal — same `_sample_next` fold throughout
            d_list, q_list = [], []
            pv = max(cur - 2, 0)
            lg, d_cache = gpt.forward_cached(
                draft_params, draft_cfg,
                jnp.asarray([[int(buf[pv]), int(buf[cur - 1])]],
                            dtype=jnp.int32),
                jnp.asarray([[pv, cur - 1]], dtype=jnp.int32), d_cache, pv,
            )
            for i in range(k):
                if i > 0:
                    p = cur - 1 + i
                    lg, d_cache = gpt.forward_cached(
                        draft_params, draft_cfg,
                        jnp.asarray([[d_list[-1]]], dtype=jnp.int32),
                        jnp.asarray([[p]], dtype=jnp.int32), d_cache, p,
                    )
                last = lg[0, -1].astype(jnp.float32)
                if temperature > 0.0:
                    adj = _adjust_logits(last, temperature, top_k)
                    qp = jax.nn.softmax(adj, axis=-1)
                    nxt = int(_sample_next(last, cur + i, key,
                                           temperature, top_k))
                else:
                    nxt = int(jnp.argmax(last))
                    qp = jax.nn.one_hot(
                        nxt, cfg.padded_vocab_size, dtype=jnp.float32
                    )
                d_list.append(nxt)
                q_list.append(qp)
            dlen = k
            d = jnp.asarray(np.asarray(d_list, np.int32))
            q = jnp.stack(q_list)
        window = np.concatenate([[buf[cur - 1]], np.asarray(d)])
        p_ids = jnp.arange(cur - 1, cur + k, dtype=jnp.int32)[None, :]
        lg, cache = gpt.forward_cached(
            params, cfg, jnp.asarray(window[None, :], dtype=jnp.int32),
            p_ids, cache, cur - 1,
        )
        lg = lg[0].astype(jnp.float32)
        if q is None:
            q = jax.nn.one_hot(d, cfg.padded_vocab_size, dtype=jnp.float32)
        accepted, cand = _accept_prefix(
            lg, d, q, jnp.int32(dlen), key, jnp.int32(cur),
            temperature, top_k,
        )
        accepted, cand = int(accepted), np.asarray(cand)
        for j in range(accepted + 1):  # the vanilla per-token gates
            if cur >= limit:  # doesn't fit: freeze, reason "length"
                active = False
                break
            t = int(cand[j])
            if t == eos_id:  # stop BEFORE appending (reference rule)
                active = False
                break
            buf[cur] = t
            cur += 1
        if cur >= limit:
            active = False
    return buf[:cur]
