"""Batched KV-cached decode primitives for the serving engine.

Round 14 (ROADMAP #1): the device half of `tpukit/serve`. Three jitted
programs generalize the single-sequence cached decode of
`tpukit/sampling.py` from batch=1 to `[N_slots, W]` with PER-SLOT state —
cursors, EOS/limit flags, rng keys — over one preallocated per-slot KV
ring (`gpt.init_kv_cache(cfg, slots, max_len)`):

  - `prefill_slots`: write an admit-batch of (bucket-padded) prompts
    into their slots' token-buffer rows and K/V ring rows in ONE
    dispatch — the "prefill" phase of phase-separated serving, batched
    so a burst of arrivals costs one forward instead of one per
    request. Bucket length and admit size are static (via the rows'
    shape), so the serve path compiles one program per (bucket,
    power-of-two admit size) pair — the declared compile budget
    (`ServeConfig.compile_budget`).
  - `decode_step`: ONE token for every slot — each slot forwards the
    token at its own cursor (a per-row `start` vector through
    `gpt.forward_cached`), samples with its own key fold, and appends
    unless it hit EOS or its length limit. One compile total, any slot
    occupancy. The "decode" phase; the host scheduler interleaves
    prefills between steps without ever stalling active slots.
  - `decode_loop`: the fused whole-batch variant (full-width prefill +
    a `lax.while_loop` of the same step body) for callers that know the
    whole batch up front — `sampling.generate_batch` and the per-epoch
    `train.generate_samples` ride this, replacing the retired O(S^2)
    re-forward loop (`_decode_loop_batch`, rounds 4-13).

Why stale cache garbage is harmless (the invariant every program here
leans on): attention masks keys at positions > the query position, and a
slot's decode writes its K/V at `cursor-1` BEFORE attending — so the
attended range `[0, cursor-1]` is always exactly the positions the
CURRENT request has written (prefill covers `[0, bucket)`, decode
rewrites from `prompt_len-1` contiguously). Leftovers from a longer
evicted request above the cursor are never read, which is what lets a
freed slot be reused with nothing but a prefill — no cache clearing,
no masked writes in the hot step.

Token parity: per slot, the math is exactly `sampling._decode_loop_cached`
— same read/write order, same `fold_in(key, cursor)` sampling fold, same
stop-before-EOS append — so the batched decode is token-for-token the
serial cached decode whatever the surrounding slots do
(tests/test_serve.py, incl. mid-stream admit/evict).

Sharded serving (`mesh`): the step runs under the training TP mesh with
params at their training shardings, the KV ring sharded over heads on
the `model` axis and slots on the `data` axis. The one deliberate
sharding constraint pins the step's sampled logits to model-replicated —
one all-gather per step at a known size — so the per-step collectives
have a closed form (`decode_step_comm`) the compiled HLO must match
(the round-10/12 audit discipline, tests/test_serve.py).

Paged KV (round 15, tpukit/serve/paged.py): when the cache pytree
carries block tables (`"bt"`), the same programs run against the page
pool — `decode_step` threads the live-slot mask into the pool
write-back, `prefill_chunk_paged` replaces the per-bucket
`prefill_slots` with chunked whole-page prefill, and
`decode_step_comm(paged=True)` extends the audit (the paged gather adds
ZERO collectives on the model-only paged grid). The ring programs and
their traces are byte-unchanged.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from tpukit.model import gpt


def _select_next(last, cursors, keys, temperature: float, top_k: int):
    """Next token per slot from f32 logits `last [N, V]`: exactly
    `sampling._sample_next` — THE one sampling spelling every decode
    loop shares — vmapped over slots. vmap semantics make each row's
    draw identical to the unbatched call, which is what the same-seed
    batched==serial parity tests pin; temperature == 0 is the greedy
    static branch (keys untouched)."""
    from tpukit.sampling import _sample_next

    if temperature > 0.0:
        return jax.vmap(
            partial(_sample_next, temperature=temperature, top_k=top_k)
        )(last, cursors, keys)
    return jnp.argmax(last, axis=-1)


def _advance(params, cfg, buf, cache, cursors, active, limits, keys,
             eos_id: int, temperature: float, top_k: int, mesh=None):
    """One decode tick for every slot (shared by `decode_step` and
    `decode_loop`'s while body). Inactive slots re-forward their last
    token into the same cache position — a write of identical values —
    and are masked out of every buffer/cursor update."""
    n, total = buf.shape
    read = jnp.clip(cursors - 1, 0, total - 1)
    tok = jnp.take_along_axis(buf, read[:, None], axis=1)
    if "bt" in cache:
        # Paged cache (round 15): the re-forward of an inactive lane must
        # NOT reach the page pool — a freed lane's block-table row may
        # alias pages the allocator has re-issued, and a prefilling lane's
        # cursor-0 write would corrupt its own first page. `write_mask`
        # routes masked rows to the null page; the ring path needs no mask
        # because each slot exclusively owns its full-width ring rows.
        logits, cache = gpt.forward_cached(
            params, cfg, tok, read[:, None].astype(jnp.int32), cache, read,
            write_mask=active, mesh=mesh,
        )
    else:
        logits, cache = gpt.forward_cached(
            params, cfg, tok, read[:, None].astype(jnp.int32), cache, read
        )
    last = logits[:, -1].astype(jnp.float32)
    if mesh is not None and "model" in mesh.axis_names:
        # Pin the sampled logits model-replicated (slots stay data-sharded):
        # ONE all-gather of the vocab-sharded head output per step, at a
        # size the closed-form audit (`decode_step_comm`) prices exactly.
        # Left to itself GSPMD picks its own (version-dependent) plan for
        # the argmax/categorical over a sharded vocab axis — unauditable.
        batch_axis = "data" if "data" in mesh.axis_names else None
        last = jax.lax.with_sharding_constraint(
            last, NamedSharding(mesh, P(batch_axis, None))
        )
    next_token = _select_next(last, cursors, keys, temperature, top_k).astype(buf.dtype)
    hit_eos = next_token == eos_id
    fits = cursors < limits
    # stop BEFORE appending on EOS (reference utils.py:67-68)
    append = active & fits & ~hit_eos
    write = jnp.clip(cursors, 0, total - 1)
    # One-hot select instead of a scatter: `buf.at[rows, write].set` makes
    # GSPMD partition a batched scatter, which drags its s32 index tensors
    # through collective-permute/all-gather plumbing on the data axis —
    # unauditable noise for a [N, W] buffer a fused elementwise select
    # writes with ZERO comm. Values are identical.
    col = jax.lax.broadcasted_iota(jnp.int32, (n, total), 1)
    hit = (col == write[:, None]) & append[:, None]
    buf = jnp.where(hit, next_token[:, None], buf)
    cursors = jnp.where(append, cursors + 1, cursors)
    active = active & fits & ~hit_eos & (cursors < limits)
    return buf, cache, cursors, active


# NOTE (container jaxlib 0.4.37): buffer donation is deliberately OMITTED
# on the serve programs. Donated executables DESERIALIZED from the
# persistent compilation cache mis-alias their inputs on this jaxlib —
# reproduced deterministically: a fresh process with a warm cache decodes
# garbage (slots with 0 or limit-overrunning generated counts) while the
# compiling process is correct, and stripping donate_argnames fixes the
# round-trip with no other change. The KV ring at test/bench scale copies
# cheaply; re-add donation when the container jaxlib moves past the bug.
@partial(
    jax.jit,
    static_argnames=("cfg", "eos_id", "temperature", "top_k", "mesh", "steps"),
)
def decode_step(params, cfg: gpt.GPTConfig, buf, cache, cursors, active,
                limits, keys, eos_id: int, temperature: float = 0.0,
                top_k: int = 0, mesh=None, steps: int = 1):
    """`steps` tokens for every slot (default 1). buf `[N, W]`, cache the
    `init_kv_cache` ring, cursors/active/limits `[N]`, keys `[N, 2]`
    uint32 (per-slot PRNG keys — ignored by the greedy trace). Returns
    the advanced `(buf, cache, cursors, active)`; a slot leaves `active`
    when it samples EOS or its cursor reaches its limit, and a slot that
    finishes mid-quantum stays FROZEN for the remaining ticks — the
    token stream is identical for any `steps`, only the host sync
    cadence changes. ONE compile per quantum size for the whole serve
    path regardless of occupancy or prompt mix.

    `steps > 1` is the decode QUANTUM: one runtime dispatch (and one
    host sync) per `steps` tokens instead of per token. Measured on the
    CPU backend a standalone dispatch costs ~5ms of host/runtime
    overhead per call while a loop-body tick costs ~1ms — per-token
    dispatch is exactly how the serial while_loop decode out-runs a
    naively-scheduled batched engine. The cost is eviction/admission
    latency quantized to `steps` ticks. The comm audit is unaffected:
    the fori_loop body appears ONCE in the compiled HLO, so
    `decode_step_comm` stays the per-step expectation at any quantum
    (tests/test_serve.py pins this)."""
    if steps == 1:
        return _advance(params, cfg, buf, cache, cursors, active, limits,
                        keys, eos_id, temperature, top_k, mesh)

    def tick(_, carry):
        buf, cache, cursors, active = carry
        return _advance(params, cfg, buf, cache, cursors, active, limits,
                        keys, eos_id, temperature, top_k, mesh)

    return jax.lax.fori_loop(0, steps, tick, (buf, cache, cursors, active))


# No donation here either — see the decode_step note (persistent-cache
# deserialization of donated executables mis-aliases on this jaxlib).
@partial(
    jax.jit,
    static_argnames=("cfg",),
)
def prefill_slots(params, cfg: gpt.GPTConfig, buf, cache, cursors, active,
                  limits, keys, slots, rows, prompt_lens, new_limits, new_keys):
    """Admit `A` requests in ONE dispatch: write their bucket-padded
    prompts `rows [A, bucket]` into the token buffer at `slots [A]` and
    prefill their K/V for positions `[0, bucket)` as ONE batched forward
    (pad positions write garbage K/V that the decode step's causal window
    never reads — module docstring). The admit-batch size A and the
    bucket are STATIC (rows' shape): compile count == distinct
    (bucket, A) pairs, which the engine bounds by padding A to a power
    of two with REPEATS of the first entry — a repeated admit rewrites
    the same slot with the same values, so dummies are idempotent.
    `slots`/`prompt_lens`/`new_limits`/`new_keys` are traced, so any
    request mix at any lanes reuses the pair's program.

    The prefill forward only materializes a `[A, bucket]`-deep scratch
    cache (the positions it writes); each admitted slot's scratch rows
    land in the big ring at `[slot, :, 0:bucket)`. Only the admitted
    lanes' state changes — active slots pass through untouched, which is
    what lets the scheduler admit mid-decode without stalling anyone."""
    a, bucket = rows.shape
    pos = jnp.broadcast_to(jnp.arange(bucket, dtype=jnp.int32), rows.shape)
    scratch = gpt.init_kv_cache(cfg, a, bucket)
    _, scratch = gpt.forward_cached(params, cfg, rows, pos, scratch, 0)
    for i in range(a):  # A is static and small (<= slots): unrolled writes
        buf = jax.lax.dynamic_update_slice(
            buf, rows[i : i + 1].astype(buf.dtype), (slots[i], 0)
        )
        cache = {
            n: jax.lax.dynamic_update_slice(
                c,
                jax.lax.dynamic_slice_in_dim(scratch[n], i, 1, axis=1),
                (0, slots[i], 0, 0, 0),
            )
            for n, c in cache.items()
        }
        cursors = cursors.at[slots[i]].set(prompt_lens[i])
        active = active.at[slots[i]].set(True)
        limits = limits.at[slots[i]].set(new_limits[i])
        keys = keys.at[slots[i]].set(new_keys[i])
    return buf, cache, cursors, active, limits, keys


# No donation — see the decode_step note (persistent-cache deserialization
# of donated executables mis-aliases on this jaxlib).
@partial(jax.jit, static_argnames=("cfg",))
def prefill_chunk_paged(params, cfg: gpt.GPTConfig, buf, cache, cursors,
                        active, limits, keys, slots, rows, starts, is_last,
                        prompt_lens, new_limits, new_keys):
    """One CHUNKED-PREFILL dispatch against the paged cache (round 15):
    forward `rows [A, C]` — each lane's next `C` prompt tokens at logical
    positions `[starts[i], starts[i] + C)` — through the lanes' block
    tables in ONE batched call, writing whole pages (`starts` page-aligned
    and C a page multiple, the engine's chunking contract; C is the
    static `ServeConfig.chunk`). A long prompt is split across scheduler
    iterations — one chunk per lane per iteration, decode quanta running
    in between — so an 8k prompt can never stall admission or active
    slots for more than one chunk's compute.

    A chunk's attention reads everything its lane's block table already
    holds: earlier chunks AND shared-prefix pages another request
    prefilled (skipping the shared compute entirely is the prefix-reuse
    win). Rows on their LAST chunk (`is_last`) arm the lane's decode
    state — cursor to the prompt length, limit, per-request key, active.
    The admit batch pads to a power of two by REPEATING entries (the
    round-14 idempotence trick: a repeated row rewrites the same pages
    and lane state with the same values), so compiles stay bounded by the
    power-of-two admit sizes — one program per (A, C) pair."""
    a, c = rows.shape
    bt = cache["bt"]
    sub = dict(cache, bt=bt[slots])  # the A lanes' block-table rows
    pos = starts[:, None] + jnp.arange(c, dtype=jnp.int32)[None, :]
    _, sub = gpt.forward_cached(params, cfg, rows, pos, sub, starts)
    cache = dict(sub, bt=bt)  # pools carry the writes; global tables kept
    for i in range(a):  # A is static and small: unrolled lane updates
        buf = jax.lax.dynamic_update_slice(
            buf, rows[i : i + 1].astype(buf.dtype), (slots[i], starts[i])
        )
        arm = is_last[i]
        cursors = jnp.where(arm, cursors.at[slots[i]].set(prompt_lens[i]), cursors)
        active = jnp.where(arm, active.at[slots[i]].set(True), active)
        limits = jnp.where(arm, limits.at[slots[i]].set(new_limits[i]), limits)
        keys = jnp.where(arm, keys.at[slots[i]].set(new_keys[i]), keys)
    return buf, cache, cursors, active, limits, keys


# No donation — see the decode_step note (persistent-cache deserialization
# of donated executables mis-aliases on this jaxlib).
@jax.jit
def adopt_slot(buf, cursors, active, limits, keys, slot, row, prompt_len,
               new_limit, new_key):
    """Arm ONE lane whose K/V was prefilled by a DIFFERENT engine (the
    disaggregated-prefill handoff, round 19, tpukit/serve/fleet.py): write
    the prompt row into the token buffer at `slot` and set the lane's
    decode state — cursor to `prompt_len`, limit, per-request key, active.
    Pure dynamic-update-slice/at-set writes, NO model forward: the page
    pool already holds the handed-off K/V (copied by fleet._copy_pages),
    so a decode replica adopting prefixes never compiles a prefill
    program — its serve-path compile budget is one decode program plus
    this trivial arm (one compile per (slots, width) shape)."""
    buf = jax.lax.dynamic_update_slice(
        buf, row[None].astype(buf.dtype), (slot, 0)
    )
    cursors = cursors.at[slot].set(prompt_len)
    active = active.at[slot].set(True)
    limits = limits.at[slot].set(new_limit)
    keys = keys.at[slot].set(new_key)
    return buf, cursors, active, limits, keys


@partial(
    jax.jit,
    static_argnames=("cfg", "max_new_tokens", "eos_id", "temperature", "top_k"),
)
def decode_loop(params, cfg: gpt.GPTConfig, buf, prompt_lens,
                max_new_tokens: int, eos_id: int, temperature: float = 0.0,
                top_k: int = 0, rng=None):
    """Fused whole-batch cached decode: prefill the full `[N, W]` buffer
    once (per-row prompt lengths are TRACED — one compile per buffer
    shape), then run the decode tick in a `lax.while_loop` until every
    row is done. Zero host round-trips inside the loop — the right shape
    when the whole batch is known up front (`sampling.generate_batch`).
    Returns `(buf, lengths)`.

    All rows share `rng` (each folds its own cursor), matching serial
    `generate(..., seed=)` per prompt. Token-for-token equal to the
    serial cached decode for every row; see the module docstring for why
    the full-width prefill's pad-position K/V garbage is never read."""
    n, total = buf.shape
    cache = gpt.init_kv_cache(cfg, n, total)
    pos = jnp.broadcast_to(jnp.arange(total, dtype=jnp.int32), buf.shape)
    _, cache = gpt.forward_cached(params, cfg, buf, pos, cache, 0)
    cursors = prompt_lens.astype(jnp.int32)
    limits = jnp.minimum(cursors + max_new_tokens, total)
    active = cursors < limits
    keys = (
        jnp.broadcast_to(rng, (n,) + rng.shape)
        if rng is not None
        else jnp.zeros((n, 2), jnp.uint32)
    )

    def cond(carry):
        return jnp.any(carry[3])

    def body(carry):
        buf, cache, cursors, active = carry
        return _advance(params, cfg, buf, cache, cursors, active, limits,
                        keys, eos_id, temperature, top_k)

    buf, _, cursors, _ = jax.lax.while_loop(
        cond, body, (buf, cache, cursors, active)
    )
    return buf, cursors


# No donation — see the decode_step note (persistent-cache deserialization
# of donated executables mis-aliases on this jaxlib).
@partial(
    jax.jit,
    static_argnames=("cfg", "eos_id", "temperature", "top_k", "mesh"),
)
def decode_loop_window(params, cfg: gpt.GPTConfig, buf, cache, cursors,
                       active, limits, keys, pages_held, max_ticks,
                       stop_when_freed, eos_id: int,
                       temperature: float = 0.0, top_k: int = 0, mesh=None):
    """On-device scheduler window (round 21, ROADMAP #3): run the decode
    tick in a `lax.while_loop` for up to `max_ticks` quanta WITHOUT any
    host sync — cursors, EOS/limit flags, and the freed-page account all
    live in the carry, so the whole window costs ONE runtime dispatch.
    PR 16's trace attribution priced the per-quantum host overhead at
    ~0.3ms dispatch against ~0.7ms device work; this loop amortizes that
    dispatch cost across the window instead of paying it every quantum.

    The loop exits early — handing control back to the host scheduler
    before the window is spent — when continuing would waste device time
    or starve admission:

      - every lane is done (`~any(active)`): nothing left to decode;
      - `freed >= stop_when_freed`: lanes that finished mid-window have
        released enough pages (`pages_held [N]` int32, each lane's
        page count, summed as lanes flip inactive) to admit the
        scheduler's head-of-queue request — the host should evict and
        admit NOW rather than let capacity idle for the rest of the
        window. Pass `1 << 30` when the queue is empty.

    `max_ticks` and `stop_when_freed` are TRACED int32 scalars: one
    compile serves every window size and page target. Returns
    `(buf, cache, cursors, active, ticks, freed)` — `ticks` is how many
    ticks actually ran (the engine's step accounting fetches it with the
    window-boundary sync, never mid-window).

    Token parity is free: the body is `_advance` — frozen lanes tick as
    no-ops and each lane's sampling folds its own cursor — so the streams
    are identical for ANY (max_ticks, early-exit) schedule; only the host
    sync cadence changes (tests/test_paged_attention.py pins loop-vs-
    repeated-`decode_step` equality under early exit). The comm audit is
    unaffected for the same reason the quantum was: the while body
    appears ONCE in the compiled HLO, so `decode_step_comm` stays the
    per-step expectation at any window (the `sched_loop` hlolint world).
    """

    def cond(carry):
        _, _, _, active, ticks, freed = carry
        return jnp.any(active) & (ticks < max_ticks) & (freed < stop_when_freed)

    def body(carry):
        buf, cache, cursors, active, ticks, freed = carry
        buf, cache, cursors, new_active = _advance(
            params, cfg, buf, cache, cursors, active, limits, keys,
            eos_id, temperature, top_k, mesh
        )
        just_done = active & ~new_active
        freed = freed + jnp.sum(jnp.where(just_done, pages_held, 0))
        return buf, cache, cursors, new_active, ticks + 1, freed

    zero = jnp.zeros((), jnp.int32)
    return jax.lax.while_loop(
        cond, body, (buf, cache, cursors, active, zero, zero)
    )


def decode_step_comm(cfg: gpt.GPTConfig, mesh, slots: int, top_k: int = 0,
                     paged: bool = False, verify_tokens: int = 1) -> dict:
    """Closed-form PER-DEVICE collective expectation for one compiled
    `decode_step` under a (data x model) serving mesh — the round-10/12
    audit discipline applied to the decode path: the compiled HLO's
    collectives must match this exactly (tests/test_serve.py).

    With params at their TensorParallel training shardings, slots (and
    the KV ring's batch axis) sharded over `data` and heads over
    `model`, the step's comm is:

      - `all-reduce` x (2*num_layers + 1): the Megatron pair per layer
        (row-parallel attn-out + ffn-down partial sums) on the
        `[N/d, 1, dim]` activations in the compute dtype, plus ONE
        f32 all-reduce for the token-embedding gather from the
        row(vocab)-sharded table (GSPMD's partial-gather lowering:
        local masked take + psum).
      - `all-gather` x 1: the deliberate logits constraint in
        `_advance` — the vocab-sharded head output gathered
        model-replicated before sampling, `[N/d, padded_vocab]` f32.
      - with top-k sampling (`top_k > 0`) and a data axis > 1, ONE more
        all-gather: `lax.top_k` is a sort and GSPMD replicates its batch
        axis over `data` — the full `[N, padded_vocab]` f32 per step, a
        real (measured, priced-in) cost of top-k truncation on a
        data-sharded slot set. Greedy and temperature-only sampling
        don't pay it.

    Precondition: `cfg.heads % model == 0` (the recipe's grid picker
    guarantees it) — with heads undividable the KV ring can't shard over
    `model` and GSPMD inserts extra resharding all-reduces around the
    cache that this formula deliberately refuses to model.

    Byte counts are RESULT payloads, the convention
    `obs.xla.collective_bytes` reports. On XLA:CPU the float wire is
    f32 (the round-12 `wire_itemsize` lesson): audit with a f32
    compute dtype for exact equality on any backend. Round 16:
    `analysis.plan.decode_comm_plan` wraps this closed form as an
    EXHAUSTIVE CommPlan (measured == expected, nothing else tolerated)
    for the hlolint rule engine (DESIGN.md §15).

    `paged=True` (round 15) extends the audit to the paged gather: the
    page pools shard heads over `model` and are REPLICATED across `data`,
    and the block tables are replicated — so the gather (page axis,
    replicated indices) and the pool write-back scatter are comm-free and
    the formula above is UNCHANGED. That only holds with a 1-sized data
    axis: data-sharded slots writing into a data-replicated pool would
    force GSPMD to reconcile the scatter with version-dependent index
    plumbing this formula refuses to model, so paged + data > 1 raises
    here (and at engine construction) instead of drifting from the HLO.

    `verify_tokens=t > 1` (round 17) prices the SPECULATIVE verify step
    (`serve/spec.verify_step`, t = spec_k + 1): the same program shape
    with every activation t positions wide — identical collective COUNTS
    (the speculation win in comm terms: t tokens of progress per
    collective round-trip) with every byte term scaled by t. The
    acceptance math itself (uniform draws, cumprod prefix, residual
    categorical) runs on the model-replicated logits and adds ZERO
    collectives — exactly why the logits pin is the one constraint.
    Speculation runs on the ring only, so `paged` and `verify_tokens>1`
    are mutually exclusive (ServeConfig enforces the same upstream).
    """
    if paged and verify_tokens > 1:
        raise ValueError(
            "speculative verify (verify_tokens > 1) audits the ring cache "
            "only — spec + paged is rejected at ServeConfig"
        )
    d = mesh.shape.get("data", 1)
    m = mesh.shape.get("model", 1)
    if paged and d > 1:
        raise ValueError(
            f"paged KV serving requires a model-only grid (data axis 1, "
            f"got data={d}): the page pool is replicated across `data`, "
            f"and a data-sharded slot set would turn the pool write-back "
            f"into an unauditable cross-shard scatter — shrink the data "
            f"axis or use the ring cache (page_size=0)"
        )
    if slots % d:
        raise ValueError(
            f"slots={slots} must be a multiple of the data axis ({d}) — "
            f"slots shard over it"
        )
    if m > 1 and cfg.heads % m:
        raise ValueError(
            f"heads={cfg.heads} must divide the model axis ({m}) for the "
            f"closed-form decode audit — undividable heads leave the KV "
            f"ring unsharded and GSPMD inserts resharding this formula "
            f"does not model"
        )
    n_local = slots // d
    t = verify_tokens
    act = n_local * t * cfg.dim * jnp.dtype(cfg.compute_dtype).itemsize
    embed = n_local * t * cfg.dim * jnp.dtype(cfg.param_dtype).itemsize
    out = {}
    if m > 1:
        out["all-reduce"] = {
            "count": 2 * cfg.num_layers + 1,
            "bytes": 2 * cfg.num_layers * act + embed,
        }
        logits = n_local * t * cfg.padded_vocab_size * 4  # f32 sample logits
        out["all-gather"] = {"count": 1, "bytes": logits}
        if top_k > 0 and d > 1:
            out["all-gather"]["count"] += 1
            out["all-gather"]["bytes"] += slots * t * cfg.padded_vocab_size * 4
    return out
