"""tpukit.serve — continuous-batching inference engine (round 14, ROADMAP #1).

Device programs (batched KV-cached decode, per-bucket prefill, the fused
whole-batch loop, the TP comm audit) in `decode.py`; the host-side slot
scheduler, request/completion types, serving telemetry and the synthetic
stream in `engine.py`; the paged KV cache — page pool + block tables,
shared-prefix registry, chunked prefill, int8 page payloads (round 15,
ROADMAP #2) — in `paged.py`; speculative decoding — draft-and-verify
with distribution-exact rejection sampling, self-speculation and draft-
model proposers (round 17, ROADMAP #3) — in `spec.py`; fleet serving —
a request router over N replica engines on disjoint device subsets,
disaggregated prefill via paged-KV handoff, occupancy autoscale,
chaos kill with exactly-once requeue (round 19, ROADMAP #1) — in
`fleet.py`; the crash-tolerance plane — durable request ledger
(write-ahead leases, exactly-once completion records, replay), the
process-fleet supervisor with real-SIGKILL chaos and heartbeat
liveness, and the ledger-driven worker loop (round 24) — in
`ledger.py`. Recipe: `main-serve.py`.
"""

from tpukit.serve import paged, spec  # noqa: F401
from tpukit.serve.decode import (  # noqa: F401
    decode_loop,
    decode_step,
    decode_step_comm,
    prefill_chunk_paged,
    prefill_slots,
)
from tpukit.serve.engine import (  # noqa: F401
    STREAM_PROFILES,
    Completion,
    Request,
    ServeConfig,
    ServeEngine,
    synthetic_request_stream,
)
from tpukit.serve.fleet import (  # noqa: F401
    FleetConfig,
    FleetRouter,
    pick_serve_grid,
)
from tpukit.serve.ledger import (  # noqa: F401
    ProcessFleet,
    RequestLedger,
    serve_from_ledger,
)
