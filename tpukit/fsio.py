"""Dependency-light filesystem primitives shared across the host side.

`atomic_write_text` is THE one tmp+rename publish spelling
(tools/lint_invariants.py enforces it): write to `<path><suffix>.tmp`,
then `os.replace` into place, so a reader never sees a torn file and
concurrent writers of the same path converge on last-writer-wins instead
of interleaving. It lives here — stdlib only, no jax/flax — because its
callers span the weight classes: checkpoint sidecars and manifests
(tpukit/checkpoint.py, which delegates), heartbeat liveness files
(obs/heartbeat.py, written every window), and the hang watchdog's
diagnostics bundles (obs/watchdog.py, written from the monitor thread at
the worst possible moment — importing a jax-heavy module there would
block the dump behind the import machinery the stuck main thread may
hold).
"""

from __future__ import annotations

import os
from pathlib import Path


def atomic_write_text(path: Path, text: str) -> None:
    """Publish `text` at `path` atomically (tmp sibling + rename).

    The tmp name appends `.tmp` to the FULL suffix (`beat.json` →
    `beat.json.tmp`), so `*.json` globs over a shared directory never
    match an in-flight write."""
    path = Path(path)
    tmp = path.with_suffix(path.suffix + ".tmp")
    tmp.write_text(text)
    os.replace(tmp, path)


def atomic_write_bytes(path: Path, blob: bytes) -> None:
    """Binary twin of `atomic_write_text` — same tmp naming, same rename
    rule (checkpoint blobs, anything a text write would mangle)."""
    path = Path(path)
    tmp = path.with_suffix(path.suffix + ".tmp")
    tmp.write_bytes(blob)
    os.replace(tmp, path)
