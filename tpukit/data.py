"""Dataset + tokenizer pipeline.

Twin of reference `data.py` (get_dataset:7-14, get_tokenizer:18-20,
transform_dataset:23-36), with one structural addition the reference lacks:
an **offline fixture path**. The reference hits the HuggingFace hub at
startup for both the TinyStories dataset and the GPT-2 tokenizer
(data.py:10-19); in a no-egress environment (and in tests — see SURVEY §4)
that is a hard failure. Here, if the hub assets are not in the local cache,
`get_dataset`/`get_tokenizer` fall back to a deterministic synthetic
TinyStories-style corpus and a word-level tokenizer with identical API
surface (`__call__` with padding/truncation, `decode(skip_special_tokens=)`,
settable `pad_token_id` — every recipe sets `pad_token_id = 2` by hand,
reference main-single.py:23).

`transform_dataset` twins the reference semantics — pad to `max_length`,
truncate, drop the text column, dense arrays out (data.py:23-36) — and
accepts either a HuggingFace dataset or the fixture dataset.
"""

from __future__ import annotations

import functools
import os
import re
from typing import Optional, Union

import numpy as np


def _hub_offline() -> None:
    """Fail fast to the fixture instead of retrying the hub for ~30s.
    Locally-cached assets still load in offline mode. Opt back into network
    fetches with TPUKIT_ALLOW_DOWNLOAD=1."""
    if os.environ.get("TPUKIT_ALLOW_DOWNLOAD") != "1":
        os.environ.setdefault("HF_HUB_OFFLINE", "1")
        os.environ.setdefault("HF_DATASETS_OFFLINE", "1")
        os.environ.setdefault("TRANSFORMERS_OFFLINE", "1")

# ---------------------------------------------------------------------------
# Synthetic TinyStories-style corpus (offline fixture).
# ---------------------------------------------------------------------------

_NAMES = ["Tom", "Lily", "Max", "Mia", "Ben", "Sue", "Sam", "Anna", "Tim", "Amy"]
_ANIMALS = ["cat", "dog", "bird", "frog", "bunny", "duck", "bear", "fox", "mouse", "pony"]
_ADJS = ["big", "small", "happy", "sad", "brown", "red", "little", "kind", "funny", "soft"]
_OBJECTS = ["ball", "hat", "book", "cake", "tree", "boat", "kite", "flower", "apple", "box"]
_PLACES = ["park", "garden", "house", "forest", "beach", "farm", "school", "yard", "pond", "hill"]
_VERBS = ["found", "saw", "liked", "wanted", "made", "took", "lost", "shared", "hugged", "chased"]

_TEMPLATES = [
    "One day, {name} went to the {place}. {name} {verb} a {adj} {obj}. "
    'She said "What a {adj} {obj}!" {name} was very {adj2}.',
    "The {adj} {adj2} {animal} lived in the {place}. One day, the {animal} {verb} a {obj}. "
    "The {animal} was {adj2} all day.",
    '{name} had a {adj} {animal}. The {animal} {verb} a {obj} in the {place}. '
    '{name} said "Good {animal}!" and they played together.',
    "One day, {name} and {name2} went to the {place}. They {verb} a {adj} {obj}. "
    '{name2} said "Let us share it." So they did, and they were {adj2}.',
    "There was a {adj} {obj} in the {place}. {name} {verb} it and showed the {animal}. "
    "The {animal} was {adj2}. The end.",
]


def synthetic_stories(num_stories: int, seed: int = 0) -> list[str]:
    """Deterministic TinyStories-like corpus for offline training and tests."""
    rng = np.random.RandomState(seed)
    stories = []
    for _ in range(num_stories):
        t = _TEMPLATES[rng.randint(len(_TEMPLATES))]
        name, name2 = rng.choice(_NAMES, 2, replace=False)
        stories.append(
            t.format(
                name=name,
                name2=name2,
                animal=rng.choice(_ANIMALS),
                adj=rng.choice(_ADJS),
                adj2=rng.choice(_ADJS),
                obj=rng.choice(_OBJECTS),
                place=rng.choice(_PLACES),
                verb=rng.choice(_VERBS),
            )
        )
    return stories


class ListDataset:
    """Minimal text dataset: a list of {"text": str} rows (fixture twin of the
    HF dataset object returned at reference data.py:10-13)."""

    def __init__(self, texts: list[str]):
        self.texts = texts

    def __len__(self):
        return len(self.texts)

    def __getitem__(self, i):
        return {"text": self.texts[i]}


# ---------------------------------------------------------------------------
# Tokenizer.
# ---------------------------------------------------------------------------

# GPT-2-style pieces: a word with optional leading space, punctuation run with
# optional leading space, or whitespace. "".join(pieces) reconstructs the text
# exactly, so decode is lossless.
_PIECE_RE = re.compile(r" ?[A-Za-z0-9']+| ?[^A-Za-z0-9\s]+|\s")

_UNK, _EOS, _PAD = 0, 1, 2  # pad at 2: every recipe sets pad_token_id = 2


class WordTokenizer:
    """Word-level tokenizer with the GPT2Tokenizer API surface the recipes
    use (reference data.py:18-20, utils.py:57,91): callable batching with
    padding/truncation, `decode(..., skip_special_tokens=)`, `vocab_size`,
    `eos_token_id`, settable `pad_token_id`, `model_max_length`.

    Unknown pieces degrade to per-character tokens (all printable ASCII chars
    are in-vocab), so any text round-trips."""

    special_tokens = ["<|unk|>", "<|endoftext|>", "<|pad|>"]

    def __init__(self, corpus: list[str], model_max_length: int = 512):
        pieces = set()
        for text in corpus:
            pieces.update(_PIECE_RE.findall(text))
        # char-level fallback alphabet
        chars = {chr(c) for c in range(32, 127)} | {"\n"}
        vocab_tokens = list(self.special_tokens) + sorted(chars | pieces)
        self._id_to_token = vocab_tokens
        self._token_to_id = {t: i for i, t in enumerate(vocab_tokens)}
        self.model_max_length = model_max_length
        self.pad_token_id = _PAD
        self.eos_token_id = _EOS
        self.unk_token_id = _UNK

    @property
    def vocab_size(self) -> int:
        return len(self._id_to_token)

    def _native_encoder(self):
        """Multithreaded C++ batch encoder (tpukit/native) — the in-tree twin
        of the reference's native fast-tokenizer + num_proc dependency path
        (reference data.py:23-36). None when no compiler is available or
        TPUKIT_NATIVE=0; output is byte-identical to the Python encoder
        (tests/test_native.py)."""
        if not hasattr(self, "_native"):
            try:
                from tpukit import native

                self._native = (
                    native.NativeEncoder(self._id_to_token, self.unk_token_id)
                    if native.is_available()
                    else None
                )
            except Exception:
                self._native = None
        return self._native

    def _encode_one(self, text: str) -> list[int]:
        ids = []
        for piece in _PIECE_RE.findall(text):
            tid = self._token_to_id.get(piece)
            if tid is not None:
                ids.append(tid)
            else:
                ids.extend(self._token_to_id.get(ch, _UNK) for ch in piece)
        return ids

    def __call__(
        self,
        texts,
        padding: Union[bool, str, None] = None,
        max_length: Optional[int] = None,
        truncation: bool = False,
        **_,
    ) -> dict:
        if isinstance(texts, str):
            texts = [texts]
        max_length = max_length or self.model_max_length
        if padding == "max_length" and truncation and len(texts) >= 64:
            native = self._native_encoder()
            if native is not None:
                ids, mask = native.encode_batch(
                    texts, max_length, self.pad_token_id
                )
                return {"input_ids": ids, "attention_mask": mask}
        encoded = [self._encode_one(t) for t in texts]
        if truncation:
            encoded = [ids[:max_length] for ids in encoded]
        if padding == "max_length":
            # Stable output contract regardless of which encoder ran: the
            # padded path always yields [N, max_length] int32 arrays (the
            # native encoder's type), never Python lists.
            input_ids = np.asarray(
                [ids + [self.pad_token_id] * (max_length - len(ids)) for ids in encoded],
                dtype=np.int32,
            )
            attention_mask = np.asarray(
                [[1] * len(ids) + [0] * (max_length - len(ids)) for ids in encoded],
                dtype=np.int32,
            )
        else:
            input_ids = encoded
            attention_mask = [[1] * len(ids) for ids in encoded]
        return {"input_ids": input_ids, "attention_mask": attention_mask}

    def decode(self, ids, skip_special_tokens: bool = False) -> str:
        pieces = []
        specials = {_UNK, _EOS, self.pad_token_id}
        for tid in np.asarray(ids).reshape(-1).tolist():
            if skip_special_tokens and tid in specials:
                continue
            if 0 <= tid < len(self._id_to_token):
                pieces.append(self._id_to_token[tid])
        return "".join(pieces)


_FIXTURE_TRAIN_SIZE = 4096
_FIXTURE_VALIDATION_SIZE = 256


@functools.lru_cache(maxsize=1)
def _fixture_corpus() -> tuple[list[str], list[str]]:
    """Memoized (round-7 host-pipeline hygiene): the corpus is deterministic
    and BOTH get_dataset and get_tokenizer rebuild it on every fit() —
    ~1.3s of pure host regeneration per run that repeat callers (bench
    probes, the test suite's ~35 fits) were paying each time. Callers treat
    the lists as read-only."""
    return (
        synthetic_stories(_FIXTURE_TRAIN_SIZE, seed=0),
        synthetic_stories(_FIXTURE_VALIDATION_SIZE, seed=1),
    )


# ---------------------------------------------------------------------------
# Public API (reference-parity surface).
# ---------------------------------------------------------------------------


def _warn_fixture_fallback(kind: str, name: str, exc: Exception) -> None:
    """Say loudly which corpus/tokenizer was actually selected: silently
    training on synthetic data when the HF path fails would be a lie in the
    reported metrics."""
    import sys

    print(
        f"tpukit: hub {kind} '{name}' unavailable "
        f"({type(exc).__name__}: {exc}); falling back to the offline "
        f"synthetic fixture {kind}",
        file=sys.stderr,
    )


def _parse_slice(n: int, slice_size: Optional[Union[str, int]]) -> int:
    """Twin of the `train[:{slice_size}]` split-string semantics at reference
    data.py:11: percent strings ("50%"), count strings ("1000"), or ints."""
    if slice_size is None or slice_size == "":
        return n
    if isinstance(slice_size, str):
        if slice_size.endswith("%"):
            return int(n * float(slice_size[:-1]) / 100.0)
        return min(int(slice_size), n)
    return min(int(slice_size), n)


def get_dataset(
    name: str = "roneneldan/TinyStories",
    slice_size: Optional[Union[str, int]] = None,
):
    """Load (train, validation) datasets. Twin of reference data.py:7-14:
    train split is sliceable, validation is always full. Falls back to the
    synthetic fixture corpus when the hub asset is not locally cached."""
    try:
        _hub_offline()
        import datasets  # type: ignore

        train = datasets.load_dataset(
            name,
            split=f"train[:{slice_size}]" if slice_size is not None else "train",
            download_mode="reuse_dataset_if_exists",
        )
        validation = datasets.load_dataset(name, split="validation")
        return train, validation
    except Exception as exc:
        _warn_fixture_fallback("dataset", name, exc)
        train_texts, validation_texts = _fixture_corpus()
        n = _parse_slice(len(train_texts), slice_size)
        return ListDataset(train_texts[:n]), ListDataset(validation_texts)


def get_tokenizer(name: str = "roneneldan/TinyStories-1M", max_length: int = 512):
    """Twin of reference data.py:18-20. HF GPT2Tokenizer when locally cached,
    else the offline WordTokenizer built over the fixture corpus."""
    try:
        _hub_offline()
        from transformers import GPT2Tokenizer  # type: ignore

        return GPT2Tokenizer.from_pretrained(
            name,
            model_max_length=max_length,
            local_files_only=os.environ.get("TPUKIT_ALLOW_DOWNLOAD") != "1",
        )
    except Exception as exc:
        _warn_fixture_fallback("tokenizer", name, exc)
        train_texts, validation_texts = _fixture_corpus()
        return WordTokenizer(train_texts + validation_texts, model_max_length=max_length)


class ArrayDataset:
    """Tokenized dataset as dense numpy arrays — the output format of
    `transform_dataset` (twin of `dataset.set_format("pt")`, reference
    data.py:35, with numpy in place of torch tensors)."""

    def __init__(self, input_ids: np.ndarray, attention_mask: np.ndarray):
        self.input_ids = input_ids
        self.attention_mask = attention_mask

    def __len__(self):
        return self.input_ids.shape[0]

    def __getitem__(self, idx):
        return {
            "input_ids": self.input_ids[idx],
            "attention_mask": self.attention_mask[idx],
        }


def transform_dataset(dataset, tokenizer, max_length: int = 512, num_proc: int = 8) -> ArrayDataset:
    """Tokenize with max-length padding + truncation and drop the text column.
    Twin of reference data.py:23-36. `num_proc` is accepted for CLI parity;
    host-side tokenization here is a single vectorized pass."""
    if hasattr(dataset, "map") and not isinstance(dataset, ListDataset):
        mapped = dataset.map(
            lambda ex: tokenizer(
                ex["text"], padding="max_length", max_length=max_length, truncation=True
            ),
            batched=True,
            remove_columns=["text"],
            num_proc=num_proc,
        )
        mapped.set_format("np")
        return ArrayDataset(
            np.asarray(mapped["input_ids"], dtype=np.int32),
            np.asarray(mapped["attention_mask"], dtype=np.int32),
        )

    texts = [dataset[i]["text"] for i in range(len(dataset))]
    out = tokenizer(texts, padding="max_length", max_length=max_length, truncation=True)
    return ArrayDataset(
        np.asarray(out["input_ids"], dtype=np.int32),
        np.asarray(out["attention_mask"], dtype=np.int32),
    )
