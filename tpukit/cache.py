"""Persistent XLA compilation cache wiring + hit/miss accounting.

JAX ships a content-addressed on-disk cache of compiled executables
(`jax_compilation_cache_dir`); with it enabled, a repeat run of the same
program skips XLA compilation entirely — on the bench ladder shapes that
is tens of seconds of host time per shape. tpukit exposes it as
`--compilation_cache_dir` (fit) and `--compilation_cache_dir` on bench.py,
and counts hits/misses through JAX's own monitoring events so the run can
LOG whether it actually hit (`kind="compile_cache"` JSONL record) instead
of leaving cache effectiveness to wall-clock guessing.

Counting: jax records `/jax/compilation_cache/compile_requests_use_cache`
once per cache-eligible compile and `/jax/compilation_cache/cache_hits`
once per hit, so `misses = requests - hits`. One module-level listener is
installed at most once per process; `enable_compilation_cache` returns a
stats handle that reports deltas since it was created, so nested scopes
(bench probes, repeated fit calls) each see their own counts.
"""

from __future__ import annotations

import os
import threading

import jax

_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_REQUEST_EVENT = "/jax/compilation_cache/compile_requests_use_cache"

_lock = threading.Lock()
_counts = {"hits": 0, "requests": 0}
_listener_installed = False


def _on_event(event: str, **kwargs) -> None:
    if event == _HIT_EVENT:
        _counts["hits"] += 1
    elif event == _REQUEST_EVENT:
        _counts["requests"] += 1


def _install_listener() -> bool:
    global _listener_installed
    with _lock:
        if _listener_installed:
            return True
        try:
            jax.monitoring.register_event_listener(_on_event)
        except Exception:
            return False  # monitoring API unavailable: fall back to file counts
        _listener_installed = True
        return True


class CompileCacheStats:
    """Delta view of the cache counters since construction, plus the cache
    directory's entry count (works even when monitoring is unavailable)."""

    def __init__(self, cache_dir: str, listener_ok: bool):
        self.cache_dir = cache_dir
        self._listener_ok = listener_ok
        self._base = dict(_counts)
        self._entries0 = self._entry_count()

    def _entry_count(self) -> int:
        try:
            return sum(
                1 for name in os.listdir(self.cache_dir)
                if not name.startswith(".")
            )
        except OSError:
            return 0

    def stats(self) -> dict:
        """JSONL-ready summary: requests/hits/misses observed since this
        handle was created, and on-disk entry growth."""
        entries = self._entry_count()
        out = {
            "dir": self.cache_dir,
            "entries": entries,
            "new_entries": entries - self._entries0,
        }
        if self._listener_ok:
            requests = _counts["requests"] - self._base["requests"]
            hits = _counts["hits"] - self._base["hits"]
            out.update(requests=requests, hits=hits, misses=requests - hits)
        return out


def enable_compilation_cache(
    cache_dir: str, min_compile_time_secs: float = 0.0
) -> CompileCacheStats:
    """Point JAX's persistent compilation cache at `cache_dir` (created if
    missing) and return a hit/miss stats handle. `min_compile_time_secs=0`
    caches every compile — the right default here, since the whole point is
    skipping repeat work and tpukit's test/bench compiles are often under
    jax's 1s default threshold."""
    cache_dir = os.path.abspath(os.path.expanduser(cache_dir))
    os.makedirs(cache_dir, exist_ok=True)
    listener_ok = _install_listener()
    previous = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", min_compile_time_secs
    )
    if previous != cache_dir:
        # jax initializes its cache object AT MOST ONCE per process, at the
        # first compile — if anything compiled before this call (or an
        # earlier call pointed elsewhere), the new dir silently never takes
        # effect. reset_cache() returns the module to its pristine state so
        # the next compile re-initializes against the dir set above.
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:
            pass  # private API moved: the dir still applies to fresh processes
    return CompileCacheStats(cache_dir, listener_ok)
