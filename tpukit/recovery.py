"""The recovery engine: exit-code contract, preemption, in-process rollback.

Rounds 6-8 gave tpukit detection for every major failure class (loss
spike/NaN sentinels, hang watchdog, heartbeat stragglers, cross-replica
divergence checksums) — but the only RESPONSE was checkpoint-then-abort.
At pod scale preemptions and transient faults are routine; a run that
aborts on the first anomaly wastes the whole fleet. Round 9 closes the
detect→recover loop with three mechanisms, all wired through `fit()`:

**Exit-code contract** (asserted by the kill-midrun harness, documented
in README): a training process exits

    0   (EXIT_CLEAN)               schedule completed, final checkpoint durable
    75  (EXIT_PREEMPTED)           SIGTERM/SIGINT received; a final
                                   checkpoint WAS written — relaunch with
                                   `--resume latest` continues bit-exact
    76  (EXIT_ANOMALY_ABORT)       sentinel abort (--spike_action abort):
                                   blown-up state checkpointed + bundle dumped
    77  (EXIT_ROLLBACK_EXHAUSTED)  --on_anomaly rollback ran out of budget
                                   (or had no restorable checkpoint) and
                                   escalated to the bundle-dump-and-abort path

75 is EX_TEMPFAIL — the sysexits meaning ("temporary failure, retry
later") matches exactly: the babysitter/scheduler should reschedule with
`--resume latest`. Round 13: the relaunch need NOT be the world that
exited — `--resume` is elastic (tpukit/reshard.py), so a scheduler that
can only get half the capacity back reshards the checkpoint onto it
instead of queueing for the original shape (docs/DESIGN.md §12). 76/77
mean "do NOT blindly restart: a human or a triage bot should read the
bundle first".

**Preemption** (`PreemptionGuard`): SIGTERM/SIGINT set a flag from the
signal handler (nothing else is async-signal-safe); the training loop
polls it at each iteration boundary and performs a GRACEFUL exit —
durable checkpoint (with resume metadata: epoch + batch position, so
`--resume latest` continues mid-epoch bit-exact), heartbeat update,
`kind="preempt"` JSONL record, then `Preempted` unwinds to the recipe
entry point which maps it to exit code 75.

**Rollback** (`RecoveryEngine`, `--on_anomaly rollback`): when a sentinel
or divergence check fires, instead of aborting the trainer restores the
last *integrity-verified* checkpoint strictly OLDER than the anomaly's
detection window (a checkpoint saved inside the window may already hold
the poisoned state), in process — no scheduler round-trip, no recompile
(the jitted step functions survive). The input stream is NOT rewound: the
loader/prefetcher keeps streaming forward, so the offending batch window
is never replayed (a deterministic bad batch would otherwise re-kill the
run on every attempt). Checkpoints from the abandoned timeline segment
are quarantined (renamed aside) so a later `latest`/rollback can never
resurrect suspect state. The budget (`--max_rollbacks`) bounds the loop;
exhaustion escalates to the round-8 bundle-dump-and-abort path with exit
code 77.

**Collective decision** (multi-process worlds): all processes must roll
back to the same step or the pod deadlocks in mismatched collectives.
Sentinel anomalies are detected by every process in lockstep (the window
loss is replicated), so each process computes the same plan locally;
process 0 additionally publishes the decision record through the
heartbeat directory (`rollback-<seq>.json`) and every other process
CONFIRMS its local plan against it before restoring — a bounded wait,
failing loud on mismatch or timeout. Divergence anomalies are detected
by process 0 only; its decision file is published one window AHEAD of
execution (`execute_after`), and every process (p0 included) executes it
at the first window boundary past that step — one window of file
propagation time on the shared filesystem, with the heartbeat timeline
counter keeping stale pre-rollback checksums out of post-rollback
divergence comparisons.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import threading
import time
from pathlib import Path
from typing import Any, Callable

from tpukit import checkpoint as ckpt_lib

# ---------------------------------------------------------------------------
# Exit-code contract
# ---------------------------------------------------------------------------

EXIT_CLEAN = 0
EXIT_PREEMPTED = 75  # EX_TEMPFAIL: checkpointed, relaunch with --resume latest
EXIT_ANOMALY_ABORT = 76
EXIT_ROLLBACK_EXHAUSTED = 77


def _atomic_write_json(path: Path, obj: dict) -> None:
    """Atomic tmp+replace publish of one coordination record — a reader
    polling the shared directory sees the whole record or nothing. (One
    atomic-publish rule for the whole package: checkpoint.py's helper.)"""
    ckpt_lib._atomic_write_text(path, json.dumps(obj))


# ---------------------------------------------------------------------------
# Heartbeat-file discipline (shared reader/writer, round 24)
#
# One atomic JSON file per publisher in a shared directory is how every
# liveness/coordination plane in tpukit talks across processes: training
# heartbeats (obs/heartbeat.py), the rollback decision records above, and
# the serving fleet's replica heartbeats (serve/fleet.py in-process,
# serve/ledger.py real worker processes). These two helpers are the shared
# spelling so the fleet's liveness plane follows the exact discipline the
# training watchdog established instead of growing a third reader.
# ---------------------------------------------------------------------------


def publish_heartbeat(directory: str | Path, name: str, record: dict) -> None:
    """Atomically publish one heartbeat record as `<directory>/<name>.json`
    — the per-publisher file a liveness reader polls. Callers stamp their
    own clock into the record (`t`): wall time for cross-process planes,
    the run clock for in-process ones."""
    _atomic_write_json(Path(directory) / f"{name}.json", record)


def read_heartbeat_dir(directory: str | Path, prefix: str = "") -> dict[str, dict]:
    """Read every heartbeat record in `directory` (optionally filtered by
    filename prefix) as {stem: record}. Torn writes can't happen (atomic
    publish) but foreign/partial files can — unparseable or vanished files
    are skipped, not fatal, exactly like obs/heartbeat.Heartbeat.read_all."""
    out: dict[str, dict] = {}
    d = Path(directory)
    if not d.is_dir():
        return out
    for path in sorted(d.glob(f"{prefix}*.json")):
        try:
            rec = json.loads(path.read_text())
        except (ValueError, OSError):
            continue
        if isinstance(rec, dict):
            out[path.stem] = rec
    return out


class TrainingAborted(RuntimeError):
    """Base of every deliberate abnormal training exit; `exit_code` is the
    process exit status the recipe entry point maps it to."""

    exit_code = 1


class AnomalyAbort(TrainingAborted):
    """Sentinel abort (--spike_action abort): state checkpointed for
    autopsy, diagnostics bundle dumped, then raised."""

    exit_code = EXIT_ANOMALY_ABORT


class RollbackBudgetExhausted(AnomalyAbort):
    """--on_anomaly rollback escalated: the budget is spent (or no
    integrity-verified checkpoint exists to restore)."""

    exit_code = EXIT_ROLLBACK_EXHAUSTED


class Preempted(TrainingAborted):
    """SIGTERM/SIGINT handled gracefully: a final checkpoint was written;
    `--resume latest` continues the run."""

    exit_code = EXIT_PREEMPTED

    def __init__(self, message: str, checkpoint: Any = None, step: int = 0):
        super().__init__(message)
        self.checkpoint = checkpoint
        self.step = step


def run_recipe(main_fn: Callable, argv=None) -> int:
    """Recipe entry-point wrapper mapping the exceptions above onto the
    documented exit codes (`sys.exit(run_recipe(main))`). Anything else
    propagates — an unexpected crash must keep its traceback and its
    nonzero (unclassified) exit status."""
    import sys

    try:
        main_fn(argv)
        return EXIT_CLEAN
    except TrainingAborted as exc:
        print(f"{type(exc).__name__}: {exc}", file=sys.stderr)
        return exc.exit_code


# ---------------------------------------------------------------------------
# Preemption
# ---------------------------------------------------------------------------


class PreemptionGuard:
    """SIGTERM/SIGINT → a polled flag. The handler only sets state (the
    async-signal-safe discipline); the training loop polls `pending` at
    iteration boundaries and runs the graceful checkpoint-and-exit path
    itself, on the training thread, where device state is coherent.

    Installed for the duration of one fit() (context manager restores the
    previous handlers — nested/test usage must not leak). Handlers can
    only be installed on the main thread; elsewhere the guard degrades to
    an inert flag (chaos `sigterm@N` still works there via the default
    handler only, so tests run fit on the main thread).

    A SECOND signal while the graceful path runs restores the previous
    handler and re-raises it — the escape hatch when the final checkpoint
    itself wedges and the scheduler escalates to SIGKILL anyway.
    """

    SIGNALS = ("SIGTERM", "SIGINT")

    def __init__(self):
        self._pending: str | None = None
        self._prev: dict[int, Any] = {}
        self._installed = False

    @property
    def pending(self) -> str | None:
        return self._pending

    def _handler(self, signum, frame):
        name = signal.Signals(signum).name
        if self._pending is not None:
            # second signal: stop being graceful
            self._restore()
            signal.raise_signal(signum)
            return
        self._pending = name

    def __enter__(self):
        if threading.current_thread() is threading.main_thread():
            for name in self.SIGNALS:
                sig = getattr(signal, name)
                self._prev[sig] = signal.signal(sig, self._handler)
            self._installed = True
        return self

    def _restore(self):
        if self._installed:
            for sig, prev in self._prev.items():
                try:
                    signal.signal(sig, prev)
                except (ValueError, OSError):  # not main thread / torn down
                    pass
            self._installed = False

    def __exit__(self, *exc):
        self._restore()
        return False


# ---------------------------------------------------------------------------
# Rollback
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class RollbackPlan:
    seq: int  # 1-based rollback counter within the run
    reason: str
    anomaly_step: int  # host step at detection (the window boundary)
    target_step: int  # checkpoint step being restored
    target_path: str  # checkpoint path (either format)
    steps_lost: int  # anomaly_step - target_step

    def record(self) -> dict:
        return dataclasses.asdict(self)


class RollbackCoordinator:
    """Decision files in the (shared) heartbeat directory: the on-disk
    channel making a multi-process rollback collective. Process 0 writes
    `rollback-<seq>.json` atomically; every process acks with
    `rollback-<seq>-ack-p<idx>.json`. Single-process worlds never touch
    the filesystem (`publish`/`confirm` short-circuit)."""

    def __init__(self, directory: str | os.PathLike | None,
                 process_index: int = 0, process_count: int = 1,
                 timeout_s: float = 120.0):
        self.directory = Path(directory) if directory else None
        self.process_index = process_index
        self.process_count = process_count
        self.timeout_s = timeout_s
        if self.directory is not None and process_count > 1:
            self.directory.mkdir(parents=True, exist_ok=True)
            # A relaunched incarnation restarts its seq counter at 1, so a
            # surviving rollback-0001.json from the PREVIOUS incarnation
            # would either execute a spurious rollback at the first window
            # boundary or (via the in-flight dedup) suppress every real
            # deferred rollback of this run. Process 0 clears the channel
            # before any rank of the new world can poll it — ranks
            # construct their coordinators during setup, whole windows
            # before the first poll.
            if self.process_index == 0:
                for stale in self.directory.glob("rollback-*.json"):
                    stale.unlink(missing_ok=True)

    def _path(self, seq: int) -> Path:
        return self.directory / f"rollback-{seq:04d}.json"

    def publish(self, plan: RollbackPlan, execute_after: int | None = None) -> None:
        """Process 0 publishes the decision (atomic tmp+rename)."""
        if self.directory is None or self.process_count == 1:
            return
        rec = plan.record()
        if execute_after is not None:
            rec["execute_after"] = execute_after
        _atomic_write_json(self._path(plan.seq), rec)

    def publish_abort(self, seq: int, reason: str, anomaly_step: int,
                      execute_after: int) -> None:
        """Process 0 publishes a collective-ABORT decision (budget spent or
        nothing restorable on a p0-only anomaly). A lone-process abort would
        strand the other ranks in the autopsy checkpoint's collective, so
        every process must reach the abort path at the same boundary —
        `poll_rollback` executes records carrying `action: "abort"`."""
        if self.directory is None or self.process_count == 1:
            return
        _atomic_write_json(self._path(seq), {
            "seq": seq, "action": "abort", "reason": reason,
            "anomaly_step": anomaly_step, "execute_after": execute_after,
        })

    def read(self, seq: int) -> dict | None:
        """The decision with sequence number `seq`, if published."""
        if self.directory is None:
            return None
        path = self._path(seq)
        try:
            return json.loads(path.read_text())
        except (OSError, ValueError):
            return None

    def confirm(self, plan: RollbackPlan) -> None:
        """Non-zero processes: wait (bounded) for process 0's decision and
        verify the locally computed plan matches it — a pod must never
        roll back to two different steps. Raises on timeout/mismatch."""
        if self.directory is None or self.process_count == 1 or self.process_index == 0:
            return
        deadline = time.monotonic() + self.timeout_s
        while True:
            rec = self.read(plan.seq)
            if rec is not None:
                if int(rec["target_step"]) != plan.target_step:
                    raise TrainingAborted(
                        f"rollback {plan.seq}: process {self.process_index} "
                        f"planned target step {plan.target_step} but process "
                        f"0 decided {rec['target_step']} — refusing a "
                        f"split-brain rollback"
                    )
                return
            if time.monotonic() > deadline:
                raise TrainingAborted(
                    f"rollback {plan.seq}: timed out after {self.timeout_s}s "
                    f"waiting for process 0's decision file in "
                    f"{self.directory}"
                )
            time.sleep(0.05)

    def ack(self, seq: int, step: int) -> None:
        if self.directory is None or self.process_count == 1:
            return
        _atomic_write_json(
            self.directory / f"rollback-{seq:04d}-ack-p{self.process_index:05d}.json",
            {"process": self.process_index, "step": step},
        )

    # -- final-drain rendezvous --------------------------------------------
    # A deferred decision published during the run's LAST training window
    # is executed at the end-of-epoch drain (train.py poll_rollback
    # final=True) — but "read the decision file once and trust None" races
    # process 0's publish: p0 detects divergence inside its last boundary
    # block (heartbeat reads + hashing, slow) while a faster rank has
    # already left the loop. The marker file closes the race: p0 writes it
    # only AFTER everything it will ever publish is on disk, and other
    # ranks must not trust a None read until the marker exists. It lives
    # in the rollback-*.json namespace so the construction-time sweep
    # clears a previous incarnation's marker.

    @property
    def _final_drain_path(self) -> Path:
        return self.directory / "rollback-final-drain.json"

    def publish_final_drain(self, step: int) -> None:
        """Process 0, entering the final drain: declare the decision
        channel complete (any pending decision is already published)."""
        if self.directory is None or self.process_count == 1 or self.process_index != 0:
            return
        _atomic_write_json(self._final_drain_path, {"step": int(step)})

    def wait_final_drain(self) -> None:
        """Non-zero ranks, entering the final drain: bounded wait for
        process 0's marker before reading the decision file — a None read
        before the marker exists proves nothing. Raises on timeout (p0
        died mid-window; proceeding could eval/save a diverged state)."""
        if self.directory is None or self.process_count == 1 or self.process_index == 0:
            return
        deadline = time.monotonic() + self.timeout_s
        while not self._final_drain_path.exists():
            if time.monotonic() > deadline:
                raise TrainingAborted(
                    f"final rollback drain: timed out after {self.timeout_s}s "
                    f"waiting for process 0's final-drain marker in "
                    f"{self.directory}"
                )
            time.sleep(0.05)


class PreemptCoordinator:
    """Decision files making a multi-process preemption checkpoint
    collective. The graceful save in `check_preempt` is a step-keyed
    collective write, but each rank polls its signal flag at its own
    wall-clock — host loops run ahead of the device frontier by up to a
    window, so two ranks observing the same SIGTERM can sit at different
    host steps and an uncoordinated save would deadlock the step-keyed
    rendezvous. Protocol: any rank whose signal lands publishes
    `preempt-request-p<idx>.json`; process 0 (at a window boundary) turns
    the first request into `preempt-decision.json` naming a window
    boundary at least one FULL window ahead; every rank's deterministic
    host-step counter passes through that boundary's poll exactly once,
    so all ranks checkpoint at the same step. Single-process worlds never
    construct this (the uncoordinated path is already correct)."""

    def __init__(self, directory: str | os.PathLike | None,
                 process_index: int = 0, process_count: int = 1):
        self.directory = Path(directory) if directory else None
        self.process_index = process_index
        self.process_count = process_count
        self._requested = False
        # The incarnation tag: fit() sets this to the run's starting
        # host_step once the (possibly resumed) state is known. Every rank
        # restores the same checkpoint, so the tag is collective without a
        # collective; records whose tag mismatches the reader's are stale
        # leftovers of a previous incarnation and are ignored. This closes
        # the relaunch race the unlink below cannot: a fast rank's first
        # poll can happen before a slow p0's init cleanup, and a resumed
        # run's host_step lands exactly on the stale decision's
        # execute_after boundary.
        self.run_start = 0
        if self.directory is not None and process_count > 1:
            self.directory.mkdir(parents=True, exist_ok=True)
            # Hygiene sweep (the tag above is the correctness guard): a
            # resumed run re-reading stale files would preempt again
            # WITHOUT any signal — every relaunch exits 75 and the run
            # never progresses. Each rank clears its own stale request;
            # process 0 clears the decision.
            (
                self.directory
                / f"preempt-request-p{self.process_index:05d}.json"
            ).unlink(missing_ok=True)
            if self.process_index == 0:
                self._decision_path.unlink(missing_ok=True)

    @property
    def _decision_path(self) -> Path:
        return self.directory / "preempt-decision.json"

    def request(self, signal_name: str) -> None:
        """Publish this rank's pending signal (idempotent, atomic)."""
        if self.directory is None or self._requested:
            return
        _atomic_write_json(
            self.directory / f"preempt-request-p{self.process_index:05d}.json",
            {
                "process": self.process_index, "signal": signal_name,
                "run_start": self.run_start,
            },
        )
        self._requested = True

    def any_request(self) -> str | None:
        """Process 0: the signal named by any published request of THIS
        incarnation (stale tags are skipped, not trusted)."""
        if self.directory is None:
            return None
        for path in sorted(self.directory.glob("preempt-request-p*.json")):
            try:
                rec = json.loads(path.read_text())
                if rec.get("run_start") != self.run_start:
                    continue  # another incarnation's leftover
                return rec["signal"]
            except (OSError, ValueError, KeyError):
                continue  # racing a partial write: next poll sees it
        return None

    def publish(self, signal_name: str, execute_after: int) -> dict:
        """Process 0 publishes the decision (idempotent: first wins)."""
        existing = self.read()
        if existing is not None:
            return existing
        rec = {
            "signal": signal_name, "execute_after": int(execute_after),
            "run_start": self.run_start,
        }
        _atomic_write_json(self._decision_path, rec)
        return rec

    def read(self) -> dict | None:
        if self.directory is None:
            return None
        try:
            rec = json.loads(self._decision_path.read_text())
        except (OSError, ValueError):
            return None
        if rec.get("run_start") != self.run_start:
            return None  # another incarnation's leftover decision
        return rec


class RecoveryEngine:
    """Budgeted in-process rollback over the run's checkpoint directory.

    `plan(reason, anomaly_step, window)` picks the newest
    integrity-verified checkpoint with step <= anomaly_step - window (a
    checkpoint saved inside the detection window may hold the poisoned
    state) and charges the budget. Returns a RollbackPlan, or None when
    the budget is spent or nothing restorable exists — the caller
    escalates to the abort path. `quarantine(plan)` renames newer
    (suspect-timeline) checkpoints aside so no later `latest` resolution
    can pick them up.
    """

    def __init__(
        self,
        directory: str | os.PathLike = "checkpoints",
        max_rollbacks: int = 3,
        coordinator: RollbackCoordinator | None = None,
    ):
        if max_rollbacks < 0:
            raise ValueError(f"max_rollbacks must be >= 0, got {max_rollbacks}")
        self.directory = Path(directory)
        self.max_rollbacks = max_rollbacks
        self.coordinator = coordinator or RollbackCoordinator(None)
        self.count = 0  # executed rollbacks
        self.steps_lost = 0
        self.exhausted = False
        self.history: list[RollbackPlan] = []

    def plan(self, reason: str, anomaly_step: int, window: int = 0) -> RollbackPlan | None:
        """Decide (do not execute) the next rollback. None = escalate."""
        if self.count >= self.max_rollbacks:
            self.exhausted = True
            return None
        max_step = anomaly_step - window
        target = ckpt_lib.latest_good(self.directory, max_step=max_step)
        if target is None:
            self.exhausted = True  # nothing restorable: same escalation
            return None
        step = ckpt_lib._step_of(target)
        return RollbackPlan(
            seq=self.count + 1,
            reason=reason,
            anomaly_step=anomaly_step,
            target_step=step,
            target_path=str(target),
            steps_lost=anomaly_step - step,
        )

    def committed(self, plan: RollbackPlan) -> None:
        """Record an executed rollback (after the restore succeeded)."""
        self.count = plan.seq
        self.steps_lost += plan.steps_lost
        self.history.append(plan)

    def quarantine(self, plan: RollbackPlan, process_zero: bool = True) -> list[str]:
        """Rename checkpoints NEWER than the rollback target aside
        (`<name>.quarantined-<seq>`): they belong to the abandoned,
        possibly-poisoned timeline segment, and the glob patterns behind
        `latest`/`latest_any` must never resolve to them again. Process-0
        only on shared filesystems (one rename per file, like the
        publish). Returns the quarantined names."""
        if not process_zero:
            return []
        out = []
        for path in ckpt_lib.all_checkpoints(self.directory):
            step = ckpt_lib._step_of(path)
            if step <= plan.target_step or str(path) == plan.target_path:
                continue
            dest = path.with_name(path.name + f".quarantined-{plan.seq:04d}")
            try:
                os.replace(path, dest)  # lint: allow(atomic-publish): quarantine RENAME of an already-published file, not a tmp+rename publish
                side = ckpt_lib.checksum_sidecar(path)
                if side.exists():
                    os.replace(  # lint: allow(atomic-publish): quarantine rename, see above
                        side, side.with_name(side.name + f".quarantined-{plan.seq:04d}")
                    )
                meta = ckpt_lib.meta_path(path)
                if meta.exists():
                    os.replace(  # lint: allow(atomic-publish): quarantine rename, see above
                        meta, meta.with_name(meta.name + f".quarantined-{plan.seq:04d}")
                    )
            except OSError:
                continue  # a quarantine miss is a warning-level event, not fatal
            out.append(dest.name)
        return out
